// Native circuit scheduler: the C++ core of quest_tpu's graph-builder.
//
// The reference's runtime around its kernels is native C (dispatch layer
// QuEST/src/QuEST.c; distributed orchestration
// QuEST/src/CPU/QuEST_cpu_distributed.c).  quest_tpu keeps the same split:
// JAX/XLA/Pallas is the compute path, and this C++ library is the runtime
// piece that *plans* a gate stream into a short program of fused cluster
// passes, fallback applies, and one-pass qubit permutations (see
// quest_tpu/circuit.py for the op semantics; the Python planner there is
// the executable specification of this algorithm, and
// tests/test_circuit.py asserts the two produce identical plans).
//
// Planning is pure integer work over gate target lists — exactly the kind
// of per-gate host-side bookkeeping that must not sit in Python when
// circuits reach millions of gates (Trotter/QAOA streams), so it is native.
//
// ABI (ctypes, see quest_tpu/native/__init__.py):
//   qts_plan(n, num_gates, offsets[num_gates+1], targets[], &buf, &len)
//     -> 0 on success; caller frees with qts_free(buf).
//
// Plan serialization (int64 stream):
//   [num_ops] then per op:
//     kind 0 (fused):   0, nA, {gate_idx, k, bits[k]} * nA,
//                          nB, {gate_idx, k, bits[k]} * nB
//     kind 1 (apply):   1, gate_idx, k, phys_targets[k]
//     kind 2 (permute): 2, n, perm[n]       (perm[new_pos] = old_pos)

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

constexpr int kLane = 7;     // qubits 0..6  -> lane cluster A
constexpr int kWindow = 14;  // qubits 0..13 -> the fused window

struct Fold {
  int64_t gate;
  std::vector<int64_t> bits;
};

struct Plan {
  std::vector<int64_t> buf;  // serialized ops (without leading count)
  int64_t num_ops = 0;
  std::vector<int64_t> pos;  // pos[logical] = physical
  std::vector<Fold> accA, accB;

  explicit Plan(int64_t n) : pos(n) {
    for (int64_t q = 0; q < n; ++q) pos[q] = q;
  }

  void flush() {
    if (accA.empty() && accB.empty()) return;
    buf.push_back(0);
    for (auto* acc : {&accA, &accB}) {
      buf.push_back(static_cast<int64_t>(acc->size()));
      for (const Fold& f : *acc) {
        buf.push_back(f.gate);
        buf.push_back(static_cast<int64_t>(f.bits.size()));
        buf.insert(buf.end(), f.bits.begin(), f.bits.end());
      }
    }
    accA.clear();
    accB.clear();
    ++num_ops;
  }

  void emit_permute(const std::vector<int64_t>& perm) {
    buf.push_back(2);
    buf.push_back(static_cast<int64_t>(perm.size()));
    buf.insert(buf.end(), perm.begin(), perm.end());
    ++num_ops;
    // content of old position perm[new] lands at new; update logical map
    std::vector<int64_t> old_to_new(perm.size());
    for (size_t np = 0; np < perm.size(); ++np) old_to_new[perm[np]] = np;
    for (auto& p : pos) p = old_to_new[p];
  }

  void emit_apply(int64_t gate, const std::vector<int64_t>& phys) {
    buf.push_back(1);
    buf.push_back(gate);
    buf.push_back(static_cast<int64_t>(phys.size()));
    buf.insert(buf.end(), phys.begin(), phys.end());
    ++num_ops;
  }
};

// 0 = cluster A, 1 = cluster B, -1 = neither
int cluster_of(const std::vector<int64_t>& phys) {
  bool a = true, b = true;
  for (int64_t p : phys) {
    if (p >= kLane) a = false;
    if (p < kLane || p >= kWindow) b = false;
  }
  if (a) return 0;
  if (b) return 1;
  return -1;
}

void fold(Plan& plan, int cl, int64_t gate, const std::vector<int64_t>& phys) {
  Fold f;
  f.gate = gate;
  for (int64_t p : phys) f.bits.push_back(cl == 0 ? p : p - kLane);
  (cl == 0 ? plan.accA : plan.accB).push_back(std::move(f));
}

}  // namespace

extern "C" {

int qts_plan(int64_t n, int64_t num_gates, const int64_t* offsets,
             const int64_t* targets, int64_t** out_buf, int64_t* out_len) {
  if (n <= 0 || num_gates < 0 || !offsets || !out_buf || !out_len) return 1;
  for (int64_t i = 0; i < offsets[num_gates]; ++i)
    if (targets[i] < 0 || targets[i] >= n) return 3;  // bad target qubit
  Plan plan(n);

  auto phys_of = [&](int64_t g) {
    std::vector<int64_t> phys;
    for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i)
      phys.push_back(plan.pos[targets[i]]);
    return phys;
  };

  if (n < kWindow) {
    // too small for the cluster kernel: plain per-gate applies
    for (int64_t g = 0; g < num_gates; ++g) plan.emit_apply(g, phys_of(g));
  } else {
    for (int64_t g = 0; g < num_gates; ++g) {
      std::vector<int64_t> phys = phys_of(g);
      int cl = cluster_of(phys);
      if (cl >= 0) {
        fold(plan, cl, g, phys);
        continue;
      }
      bool in_window = true;
      for (int64_t p : phys) in_window = in_window && p < kWindow;
      if (in_window) {
        plan.flush();
        plan.emit_apply(g, phys);
        continue;
      }
      // high target: gather the upcoming working set (first-use order)
      std::vector<int64_t> ws;
      for (int64_t h = g; h < num_gates && (int64_t)ws.size() < kWindow; ++h) {
        for (int64_t i = offsets[h]; i < offsets[h + 1]; ++i) {
          int64_t p = plan.pos[targets[i]];
          bool seen = false;
          for (int64_t w : ws) seen = seen || (w == p);
          if (!seen) ws.push_back(p);
        }
      }
      if ((int64_t)ws.size() > (n < kWindow ? n : (int64_t)kWindow))
        ws.resize(kWindow);
      plan.flush();
      std::vector<int64_t> high;
      for (int64_t p : ws)
        if (p >= kWindow) high.push_back(p);
      if (!high.empty()) {
        std::vector<bool> in_ws(n, false);
        for (int64_t p : ws) in_ws[p] = true;
        std::vector<int64_t> free_low;
        for (int64_t p = 0; p < kWindow; ++p)
          if (!in_ws[p]) free_low.push_back(p);
        std::vector<int64_t> perm(n);
        for (int64_t p = 0; p < n; ++p) perm[p] = p;
        size_t fi = 0;
        for (int64_t p : high) {
          int64_t f = free_low[fi++];
          perm[f] = p;
          perm[p] = f;
        }
        plan.emit_permute(perm);
      }
      phys = phys_of(g);
      cl = cluster_of(phys);
      if (cl >= 0) {
        fold(plan, cl, g, phys);
      } else {
        plan.flush();
        plan.emit_apply(g, phys);
      }
    }
    plan.flush();
    // restore logical order: perm[new=q] = pos[q]
    bool identity = true;
    for (int64_t q = 0; q < n; ++q) identity = identity && plan.pos[q] == q;
    if (!identity) plan.emit_permute(plan.pos);
  }
  plan.flush();

  int64_t len = static_cast<int64_t>(plan.buf.size()) + 1;
  auto* buf = static_cast<int64_t*>(std::malloc(sizeof(int64_t) * len));
  if (!buf) return 2;
  buf[0] = plan.num_ops;
  if (!plan.buf.empty())
    std::memcpy(buf + 1, plan.buf.data(), sizeof(int64_t) * plan.buf.size());
  *out_buf = buf;
  *out_len = len;
  return 0;
}

void qts_free(int64_t* buf) { std::free(buf); }

}  // extern "C"
