// Native circuit scheduler: the C++ core of quest_tpu's graph-builder.
//
// The reference's runtime around its kernels is native C (dispatch layer
// QuEST/src/QuEST.c; distributed orchestration
// QuEST/src/CPU/QuEST_cpu_distributed.c).  quest_tpu keeps the same split:
// JAX/XLA/Pallas is the compute path, and this C++ library is the runtime
// piece that *plans* a gate stream into a short program of fused cluster
// passes, fallback applies, and one-pass qubit permutations (see
// quest_tpu/circuit.py for the op semantics; the Python planner there is
// the executable specification of this algorithm, and
// tests/test_circuit.py asserts the two produce identical plans).
//
// Planning is pure integer work over gate target lists — exactly the kind
// of per-gate host-side bookkeeping that must not sit in Python when
// circuits reach millions of gates (Trotter/QAOA streams), so it is native.
//
// ABI (ctypes, see quest_tpu/native/__init__.py):
//   qts_plan(n, num_gates, offsets[num_gates+1], targets[], &buf, &len)
//     -> 0 on success; caller frees with qts_free(buf).
//
// Plan serialization (int64 stream):
//   [num_ops] then per op:
//     kind 0 (fused):   0, nA, {gate_idx, k, bits[k]} * nA,
//                          nB, {gate_idx, k, bits[k]} * nB
//     kind 1 (apply):   1, gate_idx, k, phys_targets[k]
//     kind 2 (permute): 2, n, perm[n]       (perm[new_pos] = old_pos; legacy)
//     kind 3 (segswap): 3, a, b, m          (swap bit segments [a,a+m) and
//                                            [b,b+m); see
//                                            kernels.swap_bit_segments)

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <utility>
#include <vector>

namespace {

constexpr int kLane = 7;     // qubits 0..6  -> lane cluster A
constexpr int kWindow = 14;  // qubits 0..13 -> the fused window
constexpr int64_t kLookahead = 256;  // next-use horizon for eviction choice

struct Fold {
  int64_t gate;
  std::vector<int64_t> bits;
};

struct Plan {
  std::vector<int64_t> buf;  // serialized ops (without leading count)
  int64_t num_ops = 0;
  std::vector<int64_t> pos;  // pos[logical] = physical
  std::vector<Fold> accA, accB;
  int64_t n;
  int64_t seg;                       // relocation page size
  std::vector<std::pair<int64_t, int64_t>> swap_stack;  // (h, b) per segswap

  explicit Plan(int64_t n_) : pos(n_), n(n_) {
    for (int64_t q = 0; q < n; ++q) pos[q] = q;
    seg = n - kWindow;
    if (seg > kLane) seg = kLane;
    if (seg < 0) seg = 0;
  }

  void flush() {
    if (accA.empty() && accB.empty()) return;
    buf.push_back(0);
    for (auto* acc : {&accA, &accB}) {
      buf.push_back(static_cast<int64_t>(acc->size()));
      for (const Fold& f : *acc) {
        buf.push_back(f.gate);
        buf.push_back(static_cast<int64_t>(f.bits.size()));
        buf.insert(buf.end(), f.bits.begin(), f.bits.end());
      }
    }
    accA.clear();
    accB.clear();
    ++num_ops;
  }

  void emit_segswap(int64_t h, int64_t b) {
    flush();
    buf.push_back(3);
    buf.push_back(h);
    buf.push_back(b);
    buf.push_back(seg);
    ++num_ops;
    for (auto& p : pos) {
      if (p >= b && p < b + seg)
        p = h + (p - b);
      else if (p >= h && p < h + seg)
        p = b + (p - h);
    }
  }

  void final_restore() {
    flush();
    for (auto it = swap_stack.rbegin(); it != swap_stack.rend(); ++it)
      emit_segswap(it->first, it->second);
    swap_stack.clear();
  }

  void emit_apply(int64_t gate, const std::vector<int64_t>& phys) {
    buf.push_back(1);
    buf.push_back(gate);
    buf.push_back(static_cast<int64_t>(phys.size()));
    buf.insert(buf.end(), phys.begin(), phys.end());
    ++num_ops;
  }
};

// 0 = cluster A, 1 = cluster B, -1 = neither
int cluster_of(const std::vector<int64_t>& phys) {
  bool a = true, b = true;
  for (int64_t p : phys) {
    if (p >= kLane) a = false;
    if (p < kLane || p >= kWindow) b = false;
  }
  if (a) return 0;
  if (b) return 1;
  return -1;
}

void fold(Plan& plan, int cl, int64_t gate, const std::vector<int64_t>& phys) {
  Fold f;
  f.gate = gate;
  for (int64_t p : phys) f.bits.push_back(cl == 0 ? p : p - kLane);
  (cl == 0 ? plan.accA : plan.accB).push_back(std::move(f));
}

}  // namespace

extern "C" {

int qts_plan(int64_t n, int64_t num_gates, const int64_t* offsets,
             const int64_t* targets, int64_t** out_buf, int64_t* out_len) {
  if (n <= 0 || num_gates < 0 || !offsets || !out_buf || !out_len) return 1;
  for (int64_t i = 0; i < offsets[num_gates]; ++i)
    if (targets[i] < 0 || targets[i] >= n) return 3;  // bad target qubit
  Plan plan(n);

  auto phys_of = [&](int64_t g) {
    std::vector<int64_t> phys;
    for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i)
      phys.push_back(plan.pos[targets[i]]);
    return phys;
  };

  // Mirrors _Plan.page_in in circuit.py (identical plans asserted by
  // tests/test_circuit.py): one segment swap pulling the page containing
  // all high positions of phys into the sublane window, evicting the page
  // whose occupants are needed furthest in the future.
  auto page_in = [&](int64_t g, const std::vector<int64_t>& phys) -> bool {
    const int64_t m = plan.seg;
    if (m <= 0) return false;
    int64_t hmin = -1, hmax = -1;
    for (int64_t p : phys)
      if (p >= kWindow) {
        if (hmin < 0 || p < hmin) hmin = p;
        if (p > hmax) hmax = p;
      }
    if (hmin < 0) return false;
    int64_t lo_h = std::max<int64_t>(kWindow, hmax - m + 1);
    int64_t hi_h = std::min<int64_t>(n - m, hmin);
    if (lo_h > hi_h) return false;
    const int64_t h = hi_h;
    std::vector<int64_t> cands;
    for (int64_t b = kLane; b <= kWindow - m; ++b) {
      bool ok = true;
      for (int64_t p : phys)
        if (p < kWindow && p >= b && p < b + m) ok = false;
      if (ok) cands.push_back(b);
    }
    if (cands.empty()) return false;
    int64_t best = cands[0];
    if (cands.size() > 1) {
      std::vector<int64_t> next_use(n, kLookahead + 1);
      int64_t d = 0;
      for (int64_t gg = g; gg < num_gates && d <= kLookahead; ++gg)
        for (int64_t i = offsets[gg]; i < offsets[gg + 1] && d <= kLookahead;
             ++i, ++d) {
          int64_t p = plan.pos[targets[i]];
          if (next_use[p] > d) next_use[p] = d;
        }
      int64_t best_score = -1;
      for (int64_t b : cands) {
        int64_t score = kLookahead + 1;
        for (int64_t p = b; p < b + m; ++p)
          score = std::min(score, next_use[p]);
        if (score > best_score) {
          best_score = score;
          best = b;
        }
      }
    }
    plan.emit_segswap(h, best);
    plan.swap_stack.emplace_back(h, best);
    return true;
  };

  if (n < kWindow) {
    // too small for the cluster kernel: plain per-gate applies
    for (int64_t g = 0; g < num_gates; ++g) plan.emit_apply(g, phys_of(g));
  } else {
    for (int64_t g = 0; g < num_gates; ++g) {
      std::vector<int64_t> phys = phys_of(g);
      int cl = cluster_of(phys);
      if (cl >= 0) {
        fold(plan, cl, g, phys);
        continue;
      }
      bool has_high = false;
      for (int64_t p : phys) has_high = has_high || p >= kWindow;
      if (has_high && page_in(g, phys)) {
        phys = phys_of(g);
        cl = cluster_of(phys);
        if (cl >= 0) {
          fold(plan, cl, g, phys);
          continue;
        }
      }
      // cross-cluster or un-pageable: standard layout-safe kernel
      plan.flush();
      plan.emit_apply(g, phys);
    }
    plan.final_restore();
  }
  plan.flush();

  int64_t len = static_cast<int64_t>(plan.buf.size()) + 1;
  auto* buf = static_cast<int64_t*>(std::malloc(sizeof(int64_t) * len));
  if (!buf) return 2;
  buf[0] = plan.num_ops;
  if (!plan.buf.empty())
    std::memcpy(buf + 1, plan.buf.data(), sizeof(int64_t) * plan.buf.size());
  *out_buf = buf;
  *out_len = len;
  return 0;
}

void qts_free(int64_t* buf) { std::free(buf); }

}  // extern "C"
