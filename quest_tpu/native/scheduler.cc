// Native circuit scheduler: the C++ core of quest_tpu's graph-builder.
//
// The reference's runtime around its kernels is native C (dispatch layer
// QuEST/src/QuEST.c; distributed orchestration
// QuEST/src/CPU/QuEST_cpu_distributed.c).  quest_tpu keeps the same split:
// JAX/XLA/Pallas is the compute path, and this C++ library is the runtime
// piece that *plans* a gate stream into a short program of fused cluster
// passes, fallback applies, and one-pass qubit permutations (see
// quest_tpu/circuit.py for the op semantics; the Python planner there is
// the executable specification of this algorithm, and
// tests/test_circuit.py asserts the two produce identical plans).
//
// Planning is pure integer work over gate target lists — exactly the kind
// of per-gate host-side bookkeeping that must not sit in Python when
// circuits reach millions of gates (Trotter/QAOA streams), so it is native.
//
// ABI (ctypes, see quest_tpu/native/__init__.py):
//   qts_plan(n, num_gates, offsets[num_gates+1], targets[], &buf, &len)
//     -> 0 on success; caller frees with qts_free(buf).
//
// Plan serialization (int64 stream):
//   [num_ops] then per op:
//     kind 0 (fused):   0, nEntries, {side, gate_idx, k, bits[k]} * nEntries
//                       side 0 = lane cluster A fold, 1 = sublane cluster B
//                       fold, 2 = lane-x-sublane cross fold (bits = the two
//                       physical targets; raises the Kronecker rank to 4 —
//                       see circuit._FoldAcc)
//     kind 1 (apply):   1, gate_idx, k, phys_targets[k]
//     kind 2 (permute): 2, n, perm[n]       (perm[new_pos] = old_pos; legacy)
//     kind 3 (segswap): 3, a, b, m          (swap bit segments [a,a+m) and
//                                            [b,b+m); see
//                                            kernels.swap_bit_segments)

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <utility>
#include <vector>

namespace {

constexpr int kLane = 7;     // qubits 0..6  -> lane cluster A
constexpr int kWindow = 14;  // qubits 0..13 -> the fused window
constexpr int64_t kLookahead = 256;  // next-use horizon for eviction choice

struct Fold {
  int64_t side;  // 0 = cluster A, 1 = cluster B, 2 = cross
  int64_t gate;
  std::vector<int64_t> bits;
};

constexpr int64_t kCrossRank = 4;

struct Plan {
  std::vector<int64_t> buf;  // serialized ops (without leading count)
  int64_t num_ops = 0;
  std::vector<int64_t> pos;  // pos[logical] = physical
  std::vector<Fold> acc;     // ordered fold entries since last flush
  int64_t rank = 1;          // Kronecker rank of the accumulated operator
  int64_t n;
  int64_t seg_max, seg_min;  // relocation page size bounds (see circuit.py)

  explicit Plan(int64_t n_) : pos(n_), n(n_) {
    for (int64_t q = 0; q < n; ++q) pos[q] = q;
    seg_max = n - kWindow;
    if (seg_max > kLane) seg_max = kLane;
    if (seg_max < 0) seg_max = 0;
    seg_min = seg_max > 0 ? std::min<int64_t>(3, seg_max) : 0;
  }

  void flush() {
    if (acc.empty()) return;
    buf.push_back(0);
    buf.push_back(static_cast<int64_t>(acc.size()));
    for (const Fold& f : acc) {
      buf.push_back(f.side);
      buf.push_back(f.gate);
      buf.push_back(static_cast<int64_t>(f.bits.size()));
      buf.insert(buf.end(), f.bits.begin(), f.bits.end());
    }
    acc.clear();
    rank = 1;
    ++num_ops;
  }

  void emit_segswap(int64_t h, int64_t b, int64_t m) {
    flush();
    buf.push_back(3);
    buf.push_back(h);
    buf.push_back(b);
    buf.push_back(m);
    ++num_ops;
    for (auto& p : pos) {
      if (p >= b && p < b + m)
        p = h + (p - b);
      else if (p >= h && p < h + m)
        p = b + (p - h);
    }
  }

  // Greedy block-sort back to identity (mirrors _Plan.final_restore): the
  // net permutation collapses to a handful of segment swaps instead of a
  // reverse replay of the whole swap history.
  void final_restore() {
    flush();
    for (;;) {
      int64_t q = -1;
      for (int64_t i = 0; i < n; ++i)
        if (pos[i] != i) { q = i; break; }
      if (q < 0) break;
      int64_t p = pos[q];
      int64_t m = 1;
      while (q + m < p && q + m < n && p + m < n && pos[q + m] == p + m) ++m;
      emit_segswap(p, q, m);
    }
  }

  void emit_apply(int64_t gate, const std::vector<int64_t>& phys) {
    buf.push_back(1);
    buf.push_back(gate);
    buf.push_back(static_cast<int64_t>(phys.size()));
    buf.insert(buf.end(), phys.begin(), phys.end());
    ++num_ops;
  }
};

// 0 = cluster A, 1 = cluster B, -1 = neither
int cluster_of(const std::vector<int64_t>& phys) {
  bool a = true, b = true;
  for (int64_t p : phys) {
    if (p >= kLane) a = false;
    if (p < kLane || p >= kWindow) b = false;
  }
  if (a) return 0;
  if (b) return 1;
  return -1;
}

// 2q gate with one lane and one sublane target (circuit._is_cross2)
bool is_cross2(const std::vector<int64_t>& phys) {
  if (phys.size() != 2) return false;
  int64_t a = phys[0], b = phys[1];
  return (a < kLane && b >= kLane && b < kWindow) ||
         (b < kLane && a >= kLane && a < kWindow);
}

void fold(Plan& plan, int cl, int64_t gate, const std::vector<int64_t>& phys) {
  Fold f;
  f.side = cl;
  f.gate = gate;
  for (int64_t p : phys) f.bits.push_back(cl == 0 ? p : p - kLane);
  plan.acc.push_back(std::move(f));
}

void fold_cross(Plan& plan, int64_t gate, const std::vector<int64_t>& phys) {
  Fold f;
  f.side = 2;
  f.gate = gate;
  f.bits = phys;  // physical targets in gate order
  plan.acc.push_back(std::move(f));
  plan.rank = kCrossRank;
}

}  // namespace

extern "C" {

int qts_plan(int64_t n, int64_t num_gates, const int64_t* offsets,
             const int64_t* targets, int64_t** out_buf, int64_t* out_len) {
  if (n <= 0 || num_gates < 0 || !offsets || !out_buf || !out_len) return 1;
  for (int64_t i = 0; i < offsets[num_gates]; ++i)
    if (targets[i] < 0 || targets[i] >= n) return 3;  // bad target qubit
  Plan plan(n);

  auto phys_of = [&](int64_t g) {
    std::vector<int64_t> phys;
    for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i)
      phys.push_back(plan.pos[targets[i]]);
    return phys;
  };

  // Dependency-DAG list scheduler state; mirrors plan_circuit_py in
  // circuit.py line by line (identical plans asserted by
  // tests/test_circuit.py).
  std::vector<std::vector<int64_t>> queues(n);
  for (int64_t g = 0; g < num_gates; ++g)
    for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i)
      queues[targets[i]].push_back(g);
  std::vector<int64_t> heads(n, 0);

  auto is_ready = [&](int64_t g) {
    for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i) {
      int64_t t = targets[i];
      if (heads[t] >= (int64_t)queues[t].size() || queues[t][heads[t]] != g)
        return false;
    }
    return true;
  };

  if (n < kWindow) {
    // too small for the cluster kernel: plain per-gate applies
    for (int64_t g = 0; g < num_gates; ++g) plan.emit_apply(g, phys_of(g));
  } else {
    std::vector<int64_t> ready;
    for (int64_t g = 0; g < num_gates; ++g)
      if (is_ready(g)) ready.push_back(g);
    int64_t done = 0;

    auto pop = [&](int64_t g) {
      for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i) ++heads[targets[i]];
      ++done;
      ready.erase(std::find(ready.begin(), ready.end(), g));
      for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i) {
        int64_t t = targets[i];
        if (heads[t] < (int64_t)queues[t].size()) {
          int64_t cand = queues[t][heads[t]];
          if (std::find(ready.begin(), ready.end(), cand) == ready.end() &&
              is_ready(cand))
            ready.push_back(cand);
        }
      }
      std::sort(ready.begin(), ready.end());
    };

    auto try_fold = [&](int64_t g) {
      std::vector<int64_t> phys = phys_of(g);
      int cl = cluster_of(phys);
      if (cl >= 0) {
        fold(plan, cl, g, phys);
        pop(g);
        return true;
      }
      if (is_cross2(phys)) {
        if (plan.rank > 1) plan.flush();
        fold_cross(plan, g, phys);
        pop(g);
        return true;
      }
      return false;
    };

    auto swapped_pos = [&](int64_t p, int64_t h, int64_t b, int64_t m) {
      if (p >= b && p < b + m) return h + (p - b);
      if (p >= h && p < h + m) return b + (p - h);
      return p;
    };

    // (found, h, b, m) of the segment swap enabling the most ready folds
    auto best_swap = [&](int64_t& out_h, int64_t& out_b,
                         int64_t& out_m) -> bool {
      if (plan.seg_max <= 0) return false;
      std::vector<std::pair<int64_t, int64_t>> cand_hm;
      for (int64_t g : ready) {
        int64_t hmin = -1, hmax = -1;
        for (int64_t p : phys_of(g))
          if (p >= kWindow) {
            if (hmin < 0 || p < hmin) hmin = p;
            if (p > hmax) hmax = p;
          }
        if (hmin < 0) continue;
        int64_t span = hmax - hmin + 1;
        for (int64_t m = std::max(plan.seg_min, span); m <= plan.seg_max;
             ++m) {
          int64_t lo_h = std::max<int64_t>(kWindow, hmax - m + 1);
          int64_t hi_h = std::min<int64_t>(n - m, hmin);
          if (lo_h <= hi_h &&
              std::find(cand_hm.begin(), cand_hm.end(),
                        std::make_pair(hi_h, m)) == cand_hm.end())
            cand_hm.emplace_back(hi_h, m);
        }
      }
      if (cand_hm.empty()) return false;
      std::sort(cand_hm.begin(), cand_hm.end());
      // next-use distance per physical position over pending gate-target
      // occurrences in gate-index order
      std::vector<int64_t> next_use(n, kLookahead + 1);
      int64_t d = 0;
      for (int64_t g = 0; g < num_gates && d <= kLookahead; ++g)
        for (int64_t i = offsets[g]; i < offsets[g + 1] && d <= kLookahead;
             ++i) {
          int64_t t = targets[i];
          if (heads[t] < (int64_t)queues[t].size() &&
              g >= queues[t][heads[t]]) {
            int64_t p = plan.pos[t];
            if (next_use[p] > kLookahead) next_use[p] = d;
            ++d;
          }
        }
      bool have = false;
      int64_t bc = -1, be = -1, bm = -1, bh = -1, bb = -1;
      for (auto [h, m] : cand_hm) {
        for (int64_t b = kLane; b <= kWindow - m; ++b) {
          int64_t count = 0;
          for (int64_t g : ready) {
            std::vector<int64_t> pp = phys_of(g);
            for (auto& p : pp) p = swapped_pos(p, h, b, m);
            if (cluster_of(pp) >= 0 || is_cross2(pp)) ++count;
          }
          int64_t evict = kLookahead + 1;
          for (int64_t p = b; p < b + m; ++p)
            evict = std::min(evict, next_use[p]);
          // lexicographic key (count, evict, -m, -h, -b), maximized
          bool better = false;
          if (!have) better = true;
          else if (count != bc) better = count > bc;
          else if (evict != be) better = evict > be;
          else if (m != bm) better = m < bm;
          else if (h != bh) better = h < bh;
          else if (b != bb) better = b < bb;
          if (better) {
            have = true;
            bc = count;
            be = evict;
            bm = m;
            bh = h;
            bb = b;
          }
        }
      }
      // relocating for even one foldable gate beats a standalone apply
      // pass (see circuit.py best_swap)
      if (!have || bc < 1) return false;
      out_h = bh;
      out_b = bb;
      out_m = bm;
      return true;
    };

    while (done < num_gates) {
      bool progressed = true;
      while (progressed) {
        progressed = false;
        std::vector<int64_t> snapshot = ready;
        for (int64_t g : snapshot)
          if (try_fold(g)) progressed = true;
      }
      if (done == num_gates) break;
      int64_t h, b, m;
      if (best_swap(h, b, m)) {
        plan.emit_segswap(h, b, m);
        continue;
      }
      int64_t g = ready.front();
      plan.flush();
      plan.emit_apply(g, phys_of(g));
      pop(g);
    }
    plan.final_restore();
  }
  plan.flush();

  int64_t len = static_cast<int64_t>(plan.buf.size()) + 1;
  auto* buf = static_cast<int64_t*>(std::malloc(sizeof(int64_t) * len));
  if (!buf) return 2;
  buf[0] = plan.num_ops;
  if (!plan.buf.empty())
    std::memcpy(buf + 1, plan.buf.data(), sizeof(int64_t) * plan.buf.size());
  *out_buf = buf;
  *out_len = len;
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Windowed planner (qts_plan_windowed): offset-window passes, zero
// relocation.  Mirrors circuit.plan_circuit_windowed line by line (parity
// asserted by tests/test_circuit.py::TestNativeWindowedScheduler): per pass,
// greedily pick the window offset k whose transitive fold closure over the
// ready frontier covers the most gates; 2q lane x window straddles fold at
// their operator-Schmidt rank (xranks[], computed Python-side from the
// concrete matrices), with pass rank capped at kRankCap.
//
// Serialization (int64 stream): [num_ops] then per op:
//   kind 4 (winfused): 4, k, nEntries,
//                      {side, gate_idx, nbits, bits[nbits]} * nEntries
//                      side 0 = lane A (bits = targets), 1 = window B
//                      (bits = window-relative targets), 2 = cross
//                      (bits = lane_bit, win_bit, lane_is_bit0),
//                      3 = MASK fold of a diagonal crossing gate
//                      (bits = lane_bit, win_bit, lane_is_bit0)
//   kind 1 (apply):    1, gate_idx, nt, targets[nt]
//
// flags[] per gate: bit 0 = gate matrix is diagonal (commutes with a
// pass's diagonal mask), bit 1 = concrete diagonal 2q (mask-foldable when
// it straddles lane x window).  Mirrors circuit.plan_circuit_windowed's
// gdiag/gdiag4 (the controlled-form REWRITE happens Python-side before
// planning).
// ---------------------------------------------------------------------------

namespace {

constexpr int64_t kRankCap = 4;  // keep in sync with circuit.RANK_CAP

}  // namespace

extern "C" {

int qts_plan_windowed(int64_t n, int64_t num_gates, const int64_t* offsets,
                      const int64_t* targets, const int64_t* xranks,
                      const int64_t* flags,
                      int64_t** out_buf, int64_t* out_len) {
  if (n <= 0 || num_gates < 0 || !offsets || !out_buf || !out_len) return 1;
  for (int64_t i = 0; i < offsets[num_gates]; ++i)
    if (targets[i] < 0 || targets[i] >= n) return 3;  // bad target qubit

  std::vector<int64_t> buf;
  int64_t num_ops = 0;

  auto targs_of = [&](int64_t g) {
    std::vector<int64_t> t;
    for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i)
      t.push_back(targets[i]);
    return t;
  };

  auto emit_apply = [&](int64_t g) {
    buf.push_back(1);
    buf.push_back(g);
    auto t = targs_of(g);
    buf.push_back((int64_t)t.size());
    buf.insert(buf.end(), t.begin(), t.end());
    ++num_ops;
  };

  if (n < kWindow) {
    for (int64_t g = 0; g < num_gates; ++g) emit_apply(g);
  } else {
    const int64_t k_lo = kLane, k_hi = n - kLane;

    std::vector<std::vector<int64_t>> queues(n);
    for (int64_t g = 0; g < num_gates; ++g)
      for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i)
        queues[targets[i]].push_back(g);
    std::vector<int64_t> heads(n, 0);

    auto is_ready = [&](int64_t g, const std::vector<int64_t>& hd) {
      for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i) {
        int64_t t = targets[i];
        if (hd[t] >= (int64_t)queues[t].size() || queues[t][hd[t]] != g)
          return false;
      }
      return true;
    };

    std::vector<int64_t> ready;
    for (int64_t g = 0; g < num_gates; ++g)
      if (is_ready(g, heads)) ready.push_back(g);

    auto advance = [&](int64_t g, std::vector<int64_t>& hd,
                       std::vector<int64_t>& rdy) {
      for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i) ++hd[targets[i]];
      rdy.erase(std::find(rdy.begin(), rdy.end(), g));
      for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i) {
        int64_t t = targets[i];
        if (hd[t] < (int64_t)queues[t].size()) {
          int64_t cand = queues[t][hd[t]];
          if (std::find(rdy.begin(), rdy.end(), cand) == rdy.end() &&
              is_ready(cand, hd))
            rdy.push_back(cand);
        }
      }
      std::sort(rdy.begin(), rdy.end());
    };

    // classification result: kind -1 = none, 0 = A, 1 = B, 2 = cross
    struct Cls {
      int kind;
      int64_t lane_bit, win_bit, lane_is_bit0;  // cross only
    };
    auto classify = [&](int64_t g, int64_t k) -> Cls {
      bool lane = true, win = true;
      for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i) {
        int64_t t = targets[i];
        if (t >= kLane) lane = false;
        if (t < k || t >= k + kLane) win = false;
      }
      if (lane) return {0, 0, 0, 0};
      if (win) return {1, 0, 0, 0};
      if (offsets[g + 1] - offsets[g] == 2) {
        int64_t t0 = targets[offsets[g]], t1 = targets[offsets[g] + 1];
        if (t0 < kLane && t1 >= k && t1 < k + kLane) return {2, t0, t1 - k, 1};
        if (t1 < kLane && t0 >= k && t0 < k + kLane) return {2, t1, t0 - k, 0};
      }
      return {-1, 0, 0, 0};
    };

    auto tmask_of = [&](int64_t g) {
      uint64_t m = 0;
      for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i)
        m |= (uint64_t)1 << targets[i];
      return m;
    };
    auto is_diag = [&](int64_t g) { return (flags[g] & 1) != 0; };
    auto is_diag4 = [&](int64_t g) { return (flags[g] & 2) != 0; };

    // transitive fold closure for window k over copies of the DAG state;
    // mirrors the Python mask rules: a diagonal crossing gate folds into
    // the pass mask (rank-free); once the mask is set, only gates
    // commuting with it (disjoint bits or diagonal) may keep folding
    auto simulate = [&](int64_t k, std::vector<int64_t>& folds_out,
                        int64_t& rank_out) -> int64_t {
      std::vector<int64_t> hd = heads;
      std::vector<int64_t> rdy = ready;
      int64_t rank = 1, count = 0;
      uint64_t mask_bits = 0;
      bool progressed = true;
      while (progressed) {
        progressed = false;
        std::vector<int64_t> snapshot = rdy;
        for (int64_t g : snapshot) {
          if (std::find(rdy.begin(), rdy.end(), g) == rdy.end()) continue;
          Cls c = classify(g, k);
          if (c.kind < 0) continue;
          bool blocked = mask_bits && !is_diag(g) && (mask_bits & tmask_of(g));
          if (c.kind == 2) {
            if (is_diag4(g)) {
              mask_bits |= tmask_of(g);
            } else {
              if (blocked) continue;
              int64_t r = xranks[g];
              if (rank * r > kRankCap) continue;
              rank *= r;
            }
          } else if (blocked) {
            continue;
          }
          ++count;
          folds_out.push_back(g);
          advance(g, hd, rdy);
          progressed = true;
        }
      }
      rank_out = rank;
      return count;
    };

    while (!ready.empty()) {
      // candidate offsets: windows covering some ready gate's high targets,
      // plus the home window k=7
      std::vector<int64_t> cands{k_lo};
      for (int64_t g : ready)
        for (int64_t i = offsets[g]; i < offsets[g + 1]; ++i) {
          int64_t t = targets[i];
          if (t >= kLane) {
            int64_t lo = std::max(k_lo, t - kLane + 1);
            int64_t hi = std::min(k_hi, t);
            for (int64_t k = lo; k <= hi; ++k) {
              // k in {8, 9} forces the collapsed 4-d state view whose
              // layout breaks the canonical tiling (full-state retile
              // copies at pass boundaries; OOM at 30q) — pruned here;
              // gates ONLY those windows cover (spanning exactly bits
              // [8,14] / [9,15]) are caught by the last-resort retry
              // below.  Mirrors circuit.plan_circuit_windowed.
              if (k_hi >= 10 && (k == 8 || k == 9)) continue;
              if (std::find(cands.begin(), cands.end(), k) == cands.end())
                cands.push_back(k);
            }
          }
        }
      std::sort(cands.begin(), cands.end());

      bool have = false;
      int64_t bcount = 0, brank = 0, bk = 0;
      std::vector<int64_t> bfolds;
      for (int64_t k : cands) {
        std::vector<int64_t> folds;
        int64_t rank;
        int64_t count = simulate(k, folds, rank);
        // lexicographic key (count, -rank, -k), maximized
        bool better = false;
        if (!have) better = true;
        else if (count != bcount) better = count > bcount;
        else if (rank != brank) better = rank < brank;
        else if (k != bk) better = k < bk;
        if (better) {
          have = true;
          bcount = count;
          brank = rank;
          bk = k;
          bfolds = std::move(folds);
        }
      }
      if (!have || bcount == 0) {
        // last resort: retry the pruned offsets {8, 9} — a gate spanning
        // exactly bits [8,14] or [9,15] has no other covering window
        for (int64_t k = 8; k <= 9; ++k) {
          if (k < k_lo || k > k_hi) continue;
          std::vector<int64_t> folds;
          int64_t rank;
          int64_t count = simulate(k, folds, rank);
          bool better = false;
          if (count == 0) continue;
          if (!have || bcount == 0) better = true;
          else if (count != bcount) better = count > bcount;
          else if (rank != brank) better = rank < brank;
          else better = k < bk;
          if (better) {
            have = true;
            bcount = count;
            brank = rank;
            bk = k;
            bfolds = std::move(folds);
          }
        }
      }
      if (!have || bcount == 0) {
        int64_t g = ready.front();
        emit_apply(g);
        advance(g, heads, ready);
        continue;
      }
      buf.push_back(4);
      buf.push_back(bk);
      buf.push_back((int64_t)bfolds.size());
      for (int64_t g : bfolds) {
        Cls c = classify(g, bk);
        int64_t kind = (c.kind == 2 && is_diag4(g)) ? 3 : c.kind;
        buf.push_back(kind);
        buf.push_back(g);
        if (c.kind == 2) {
          buf.push_back(3);
          buf.push_back(c.lane_bit);
          buf.push_back(c.win_bit);
          buf.push_back(c.lane_is_bit0);
        } else {
          auto t = targs_of(g);
          buf.push_back((int64_t)t.size());
          for (int64_t tt : t) buf.push_back(c.kind == 0 ? tt : tt - bk);
        }
        advance(g, heads, ready);
      }
      ++num_ops;
    }
  }

  int64_t len = (int64_t)buf.size() + 1;
  auto* out = static_cast<int64_t*>(std::malloc(sizeof(int64_t) * len));
  if (!out) return 2;
  out[0] = num_ops;
  if (!buf.empty())
    std::memcpy(out + 1, buf.data(), sizeof(int64_t) * buf.size());
  *out_buf = out;
  *out_len = len;
  return 0;
}

void qts_free(int64_t* buf) { std::free(buf); }

}  // extern "C"
