"""ctypes binding + on-demand build of the native C++ circuit scheduler.

The library (scheduler.cc) is compiled once with g++ into _qts.so next to
this file; if the toolchain is unavailable the import degrades gracefully
and circuit.py falls back to its Python planner (same algorithm — the
native path exists for million-gate streams where per-gate Python
bookkeeping dominates).  Disable with QT_NATIVE=0.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "scheduler.cc")
_LIB = os.path.join(_DIR, "_qts.so")

_lock = threading.Lock()
_lib = None
_build_failed = False


def _build() -> bool:
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _LIB)  # atomic: concurrent readers never see a torn .so
        return True
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def get_lib():
    """Load (building if needed) the native scheduler; None if unavailable."""
    global _lib, _build_failed
    if os.environ.get("QT_NATIVE", "1") == "0":
        return None
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not os.path.exists(_LIB) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        ):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _build_failed = True
            return None
        lib.qts_plan.restype = ctypes.c_int
        lib.qts_plan.argtypes = [
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.qts_free.restype = None
        lib.qts_free.argtypes = [ctypes.POINTER(ctypes.c_int64)]
        try:
            lib.qts_plan_windowed.restype = ctypes.c_int
            lib.qts_plan_windowed.argtypes = [
                ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
                ctypes.POINTER(ctypes.c_int64),
            ]
        except AttributeError:  # older _qts.so without the windowed planner
            pass
        _lib = lib
        return _lib


def native_available() -> bool:
    return get_lib() is not None


def plan_native_windowed(target_lists: Sequence[Sequence[int]],
                         num_qubits: int,
                         xranks: Sequence[int],
                         flags: Optional[Sequence[int]] = None,
                         ) -> Optional[List[tuple]]:
    """Run the C++ windowed planner (qts_plan_windowed) over gate target
    lists + per-gate cross ranks and diagonality flags (bit 0 = diagonal
    matrix, bit 1 = diagonal 2q, mask-foldable when crossing).  Returns a
    structural plan —
      ('winfused', k, [(kind, gate_idx, bits), ...])  kind: 0=A, 1=B,
        2=cross, 3=mask, both with bits=(lane_bit, win_bit, lane_is_bit0)
      ('apply', gate_idx, targets)
    — or None when the native library (or entry point) is unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "qts_plan_windowed"):
        return None
    offsets = np.zeros(len(target_lists) + 1, dtype=np.int64)
    for i, t in enumerate(target_lists):
        offsets[i + 1] = offsets[i] + len(t)
    flat = np.fromiter(
        (q for t in target_lists for q in t), dtype=np.int64,
        count=int(offsets[-1]),
    )
    if flat.size == 0:
        flat = np.zeros(1, dtype=np.int64)
    xr = np.asarray(list(xranks), dtype=np.int64)
    if xr.size == 0:
        xr = np.zeros(1, dtype=np.int64)
    if flags is None:
        flags = [0] * len(target_lists)
    fl = np.asarray(list(flags), dtype=np.int64)
    if fl.size == 0:
        fl = np.zeros(1, dtype=np.int64)
    buf = ctypes.POINTER(ctypes.c_int64)()
    length = ctypes.c_int64()
    rc = lib.qts_plan_windowed(
        num_qubits, len(target_lists),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        xr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        fl.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.byref(buf), ctypes.byref(length),
    )
    if rc != 0:
        return None
    try:
        data = np.ctypeslib.as_array(buf, shape=(length.value,)).copy()
    finally:
        lib.qts_free(buf)

    ops: List[tuple] = []
    i = 1
    for _ in range(int(data[0])):
        kind = int(data[i]); i += 1
        if kind == 4:
            k = int(data[i]); nf = int(data[i + 1]); i += 2
            entries = []
            for _f in range(nf):
                side = int(data[i]); gi = int(data[i + 1])
                nb = int(data[i + 2]); i += 3
                bits = tuple(int(b) for b in data[i:i + nb]); i += nb
                entries.append((side, gi, bits))
            ops.append(("winfused", k, entries))
        elif kind == 1:
            gi = int(data[i]); nt = int(data[i + 1]); i += 2
            targs = tuple(int(p) for p in data[i:i + nt]); i += nt
            ops.append(("apply", gi, targs))
        else:
            raise ValueError(f"bad windowed plan op kind {kind}")
    return ops


def plan_native(target_lists: Sequence[Sequence[int]],
                num_qubits: int) -> Optional[List[tuple]]:
    """Run the C++ planner over gate target lists.

    Returns a *structural* plan — ops referencing gates by index:
      ('fused', [(side, gate_idx, bits), ...])   side: 0=A, 1=B, 2=cross
      ('apply', gate_idx, phys_targets)
      ('segswap', a, b, m)
    or None when the native library is unavailable.
    """
    lib = get_lib()
    if lib is None:
        return None
    offsets = np.zeros(len(target_lists) + 1, dtype=np.int64)
    for i, t in enumerate(target_lists):
        offsets[i + 1] = offsets[i] + len(t)
    flat = np.fromiter(
        (q for t in target_lists for q in t), dtype=np.int64,
        count=int(offsets[-1]),
    )
    if flat.size == 0:
        flat = np.zeros(1, dtype=np.int64)  # valid pointer for ctypes
    buf = ctypes.POINTER(ctypes.c_int64)()
    length = ctypes.c_int64()
    rc = lib.qts_plan(
        num_qubits, len(target_lists),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.byref(buf), ctypes.byref(length),
    )
    if rc != 0:
        return None
    try:
        data = np.ctypeslib.as_array(buf, shape=(length.value,)).copy()
    finally:
        lib.qts_free(buf)

    ops: List[tuple] = []
    i = 1
    for _ in range(int(data[0])):
        kind = int(data[i]); i += 1
        if kind == 0:
            nf = int(data[i]); i += 1
            entries = []
            for _f in range(nf):
                side = int(data[i]); gi = int(data[i + 1])
                k = int(data[i + 2]); i += 3
                bits = tuple(int(b) for b in data[i:i + k]); i += k
                entries.append((side, gi, bits))
            ops.append(("fused", entries))
        elif kind == 1:
            gi = int(data[i]); k = int(data[i + 1]); i += 2
            phys = tuple(int(p) for p in data[i:i + k]); i += k
            ops.append(("apply", gi, phys))
        elif kind == 2:
            k = int(data[i]); i += 1
            perm = tuple(int(p) for p in data[i:i + k]); i += k
            ops.append(("permute", perm))
        elif kind == 3:
            a = int(data[i]); b = int(data[i + 1]); m = int(data[i + 2]); i += 3
            ops.append(("segswap", a, b, m))
        else:
            raise ValueError(f"bad plan op kind {kind}")
    return ops
