"""quest_tpu: a TPU-native quantum simulation framework.

A ground-up JAX/XLA/Pallas re-design with the full capability surface of the
reference QuEST library (state-vectors + density matrices, ~140 API
functions, distributed amplitude sharding): see SURVEY.md for the layer map
and reference citations.

Quick start::

    import quest_tpu as qt

    env = qt.createQuESTEnv()
    q = qt.createQureg(3, env)
    qt.hadamard(q, 0)
    qt.controlledNot(q, 0, 1)
    print(qt.calcProbOfOutcome(q, 1, 1))   # 0.5

The camelCase API mirrors the reference (QuEST.h) so existing QuEST users
can switch directly; list arguments carry their own lengths, replacing the
C API's explicit count parameters.
"""

from .precision import (
    set_precision,
    get_precision,
    real_eps,
    MAX_NUM_REGS_APPLY_ARBITRARY_PHASE,
)
from .validation import QuESTError
from .qureg import Qureg, PauliHamil, DiagonalOp
from .env import QuESTEnv
from .qasm import QASMLogger
from .api import *  # noqa: F401,F403
from .fusion import (
    gate_fusion as gateFusion,
    start_gate_fusion as startGateFusion,
    stop_gate_fusion as stopGateFusion,
)
from .api_ops import *  # noqa: F401,F403
from .checkpoint import (
    saveQureg,
    loadQureg,
    writeStateToFile,
    readStateFromFile,
)
from .resilience import (
    run_resumable as runResumable,
    run_resumable,
    check_qureg_health as checkQuregHealth,
    FaultPlan,
    SimulatedPreemption,
    NumericalHealthError,
    WindowExecutor,
    degradation_report,
)
from . import serve
from .serve import (
    SimServer,
    Service as SimService,
    Service,
    Job,
    Tenant,
    QuotaExceededError,
)
from .batch import (
    BatchedQureg,
    EnsembleScheduler,
    createBatchedQureg,
    applyBatchedUnitary,
    measureBatched,
    calcExpecPauliSumBatched,
    run_trajectories,
    run_trajectories as runTrajectories,
)
from .debug import (
    initStateOfSingleQubit,
    initStateFromSingleFile,
    compareStates,
)
from . import telemetry
from .telemetry import report_perf as reportPerf, report_perf
from . import governor
from .governor import MemoryAdmissionError
from . import optimizer
from .optimizer import (
    set_circuit_optimizer,
    get_circuit_optimizer,
)
from . import introspect
from .introspect import (
    explain_circuit,
    explain_circuit as explainCircuit,
    report_circuit_plan,
    report_circuit_plan as reportCircuitPlan,
    audit,
    CollectiveBudget,
)
from .ops import phasefunc as _pf

# enum phaseFunc (QuEST.h:231-234)
NORM = _pf.NORM
SCALED_NORM = _pf.SCALED_NORM
INVERSE_NORM = _pf.INVERSE_NORM
SCALED_INVERSE_NORM = _pf.SCALED_INVERSE_NORM
SCALED_INVERSE_SHIFTED_NORM = _pf.SCALED_INVERSE_SHIFTED_NORM
PRODUCT = _pf.PRODUCT
SCALED_PRODUCT = _pf.SCALED_PRODUCT
INVERSE_PRODUCT = _pf.INVERSE_PRODUCT
SCALED_INVERSE_PRODUCT = _pf.SCALED_INVERSE_PRODUCT
DISTANCE = _pf.DISTANCE
SCALED_DISTANCE = _pf.SCALED_DISTANCE
INVERSE_DISTANCE = _pf.INVERSE_DISTANCE
SCALED_INVERSE_DISTANCE = _pf.SCALED_INVERSE_DISTANCE
SCALED_INVERSE_SHIFTED_DISTANCE = _pf.SCALED_INVERSE_SHIFTED_DISTANCE

# bitEncoding (QuEST.h:269)
UNSIGNED = 0
TWOS_COMPLEMENT = 1

# pauliOpType (QuEST.h:96)
PAULI_I, PAULI_X, PAULI_Y, PAULI_Z = 0, 1, 2, 3

__version__ = "0.1.0"
