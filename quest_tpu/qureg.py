"""Register and operator data structures.

TPU-native analogues of the reference's user types:

- ``Qureg`` (QuEST.h:322-353): the amplitude array is a single (possibly
  sharded) on-HBM ``jax.Array`` instead of SoA real/imag C buffers; there is
  no pairStateVec (the reference's 2x distributed receive buffer,
  QuEST_cpu.c:1279-1315) because collective permutes materialize only
  transient buffers, and no host mirror (the reference GPU backend keeps a
  full CPU copy, QuEST_gpu.cu:275-319).
- ``PauliHamil`` (QuEST.h:277): codes as an (terms, qubits) int array plus a
  coefficient vector — device-resident so expectation values trace cleanly.
- ``DiagonalOp`` (QuEST.h:297): a sharded complex diagonal kept as real+imag
  pairs, mirroring the reference's SoA layout at the API level.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import precision
from .env import QuESTEnv
from .qasm import QASMLogger


class Qureg:
    """A quantum register: pure state-vector or density matrix.

    ``amps`` is a real SoA array of shape (2, 2^numQubitsInStateVec)
    (channel 0/1 = real/imag — the reference's ComplexArray layout,
    QuEST.h:77; see ops/cplx.py for why this is the TPU-native choice),
    sharded over the env's amplitude mesh on its amplitude axis by leading
    (most-significant-bit) index — the reference's chunkId scheme
    (QuEST.h:330-338) as a NamedSharding.
    """

    def __init__(self, num_qubits: int, env: QuESTEnv, is_density_matrix: bool):
        self.is_density_matrix = bool(is_density_matrix)
        self.num_qubits_represented = int(num_qubits)
        self.num_qubits_in_state_vec = (2 if is_density_matrix else 1) * int(num_qubits)
        self.env = env
        self.dtype = precision.real_dtype()  # SoA channels are real arrays
        self.qasm_log = QASMLogger(num_qubits)
        self._amps: Optional[jax.Array] = None
        self._fusion = None  # FusionBuffer while a gateFusion context is active
        # governor.SpillHandle while the amplitudes live on host (the
        # memory governor's spill-to-host eviction); restored lazily on
        # the next touch via the amps getters below
        self._spill = None
        # live logical->physical qubit permutation of a SHARDED register
        # (None = canonical order).  _perm[q] = physical state-vector bit
        # holding logical bit q: the communication-avoiding scheduler keeps
        # the state permuted across windows and only rematerializes
        # canonical order on a state read (the ``amps`` getter below) —
        # see parallel/dist.py remap_sharded.
        self._perm: Optional[tuple] = None
        # last-use tick per logical state-vector bit: the relocalizer
        # evicts the least-recently-used residents so an alternating
        # circuit never ping-pongs its hot qubits across the shard
        # boundary
        self._last_use: dict = {}
        self._use_clock: int = 0
        # fusion drains executed on this register (window-boundary
        # accounting for the resilience layer's checkpoint cadence)
        self._drain_count: int = 0

    # -- reference-parity metadata (QuEST.h:330-345) --
    @property
    def num_amps_total(self) -> int:
        return 1 << self.num_qubits_in_state_vec

    @property
    def num_chunks(self) -> int:
        return self.env.num_devices

    @property
    def num_amps_per_chunk(self) -> int:
        return self.num_amps_total // self.num_chunks

    @property
    def amps(self) -> jax.Array:
        """Amplitudes in CANONICAL qubit order: pending fused gates drain
        first, then a live logical->physical permutation (left behind by
        the communication-avoiding scheduler) is rematerialized with ONE
        batched remap — so every reader (calculations, measurement,
        checkpointing, host gathers) sees reference semantics."""
        if self._amps is None:
            from . import governor, validation

            if not governor.restore_register(self):
                raise validation.QuESTError(
                    "Qureg: the register has been destroyed (destroyQureg) "
                    "or never initialised."
                )
        if self._fusion is not None and self._fusion.gates:
            from . import fusion

            fusion.drain(self)  # may leave a live permutation
        if self._perm is not None:
            from .parallel import dist as PAR

            self._amps = PAR.remap_sharded(
                self._amps, mesh=self.env.mesh,
                num_qubits=self.num_qubits_in_state_vec,
                sigma=PAR.canonical_sigma(self._perm))
            self._perm = None
        return self._amps

    @amps.setter
    def amps(self, value: jax.Array):
        if self._fusion is not None and self._fusion.gates:
            # a pure overwrite makes pending gates unobservable (any RHS
            # that depended on the old state already drained via the
            # getter) — discard them instead of computing a dead result
            self._fusion.gates.clear()
        # external overwrites are canonical-order by contract; only the
        # perm-aware writers (_set_amps_permuted) carry a permutation over
        self._perm = None
        self._spill = None  # an overwrite invalidates any host snapshot
        self._amps = value

    def _amps_raw(self) -> jax.Array:
        """Amplitudes WITHOUT rematerializing canonical order — the
        perm-aware dispatch path's read (pending fused gates still drain
        first so operation order is preserved)."""
        if self._amps is None:
            from . import governor

            if not governor.restore_register(self):
                return self.amps  # raises the destroyed-register error
        if self._fusion is not None and self._fusion.gates:
            from . import fusion

            fusion.drain(self)
        return self._amps

    def _set_amps_permuted(self, value: jax.Array, perm) -> None:
        """Rebind amplitudes held under logical->physical ``perm``
        (identity or None -> canonical).  Unlike the ``amps`` setter this
        PRESERVES the lazy-permutation bookkeeping."""
        self._spill = None
        self._amps = value
        if perm is not None and tuple(perm) == tuple(
                range(self.num_qubits_in_state_vec)):
            perm = None
        self._perm = None if perm is None else tuple(perm)

    def bind_checkpoint_state(self, amps: jax.Array, perm, dtype) -> None:
        """Rebind this register to checkpointed state: raw (possibly
        permuted) amplitudes, the live logical->physical permutation, and
        the dtype the snapshot was taken at — the restore half of the
        resilience layer's generation protocol (resilience.py).  Unlike
        the ``amps`` setter this preserves the permutation; any pending
        fused gates are discarded (they predate the snapshot)."""
        if self._fusion is not None and self._fusion.gates:
            self._fusion.gates.clear()
        self.dtype = np.dtype(dtype)
        self._set_amps_permuted(amps, perm)

    def reshard_to(self, env: QuESTEnv) -> None:
        """Move this register onto ``env``'s mesh in place, carrying any
        live logical->physical permutation over unchanged (the perm is a
        bit permutation of the GLOBAL amplitude index — mesh-shape-
        independent; see resilience._validated_perm).  Pending fused
        gates drain on the OLD mesh first so operation order is
        preserved; subsequent windows plan against the new mesh's shard
        split (fusion keys its plans on nloc, so nothing stale
        survives).  This is the live-state half of elastic recovery —
        checkpointed restores instead reshard on read
        (resilience.load_latest)."""
        amps = self._amps_raw()  # drain pending gates on the old mesh
        perm = self._perm
        self.env = env
        self._amps = jax.device_put(amps, self.sharding())
        self._perm = perm

    def _phys_bits(self, bits) -> tuple:
        """Physical positions of logical state-vector bits under the live
        permutation (identity when none is active)."""
        if self._perm is None:
            return tuple(bits)
        return tuple(self._perm[b] for b in bits)

    def sharding(self):
        if self.num_amps_total >= self.env.num_devices:
            return self.env.amp_sharding()
        return self.env.replicated_sharding()

    def device_put(self, amps) -> jax.Array:
        return jax.device_put(jnp.asarray(amps, self.dtype), self.sharding())


class PauliHamil:
    """Real-weighted sum of Pauli products (QuEST.h:277)."""

    def __init__(self, num_qubits: int, num_sum_terms: int):
        self.num_qubits = int(num_qubits)
        self.num_sum_terms = int(num_sum_terms)
        self.pauli_codes = np.zeros((num_sum_terms, num_qubits), dtype=np.int32)
        self.term_coeffs = np.zeros((num_sum_terms,), dtype=np.float64)


class DiagonalOp:
    """Diagonal operator on the full Hilbert space (QuEST.h:297).  Stored as
    real+imag vectors (SoA like the reference) of length 2^numQubits, sharded
    over the amplitude mesh by the same leading-bit scheme."""

    def __init__(self, num_qubits: int, env: QuESTEnv):
        self.num_qubits = int(num_qubits)
        self.env = env
        rdt = precision.real_dtype()
        dim = 1 << self.num_qubits
        sharding = env.sharding_for_dim(dim)
        self.real = jax.device_put(jnp.zeros((dim,), rdt), sharding)
        self.imag = jax.device_put(jnp.zeros((dim,), rdt), sharding)

    @property
    def num_elems_per_chunk(self) -> int:
        return (1 << self.num_qubits) // self.env.num_devices
