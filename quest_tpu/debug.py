"""Debug / test-support API — the reference's QuEST_debug.h surface.

Non-public hooks the reference exposes for its own test harness
(QuEST/src/QuEST_debug.h): single-qubit classical init, state file
loading, and amp-wise state comparison.  ``initDebugState`` and
``setDensityAmps`` live in the main API (api.py) as in the reference.
"""

from __future__ import annotations

import math

import numpy as np

from . import validation as V
from .checkpoint import readStateFromFile
from .env import QuESTEnv
from .qureg import Qureg


def initStateOfSingleQubit(qureg: Qureg, qubitId: int, outcome: int) -> None:
    """Uniform superposition over all basis states whose ``qubitId`` bit
    equals ``outcome`` (statevec_initStateOfSingleQubit,
    QuEST_cpu.c — normFactor 1/sqrt(2^n / 2))."""
    V.validate_target(qureg, qubitId, "initStateOfSingleQubit")
    V.validate_outcome(outcome, "initStateOfSingleQubit")
    n = qureg.num_qubits_in_state_vec
    dim = 1 << n
    norm = 1.0 / math.sqrt(dim / 2.0)
    idx = np.arange(dim)
    re = np.where(((idx >> qubitId) & 1) == outcome, norm, 0.0)
    qureg.amps = qureg.device_put(np.stack([re, np.zeros(dim)]))


def initStateFromSingleFile(qureg: Qureg, filename: str,
                            env: QuESTEnv | None = None) -> bool:
    """Load amplitudes from a reference-format CSV file; returns success
    (statevec_initStateFromSingleFile, QuEST_cpu.c:1680-1729)."""
    return readStateFromFile(qureg, filename)


def _guard_host_gather(qureg: Qureg, func: str) -> None:
    """Refuse to gather a full state to one host buffer beyond the
    reference's message cap (MPI_MAX_AMPS_IN_MSG — the reference's
    toQVector guard, utilities.cpp:1073-1074): at 30q+ the gather is also
    a full-state device relayout (the round-3 OOM trap, BASELINE.md)."""
    from .precision import max_amps_in_msg

    if qureg.num_amps_total > max_amps_in_msg():
        raise V.QuESTError(
            f"{func}: State has too many amplitudes "
            f"({qureg.num_amps_total} > {max_amps_in_msg()}) to gather to "
            "a single host buffer; use getAmp/reportState per chunk "
            "instead.")


def compareStates(qureg1: Qureg, qureg2: Qureg, precision: float) -> bool:
    """Amp-wise |re1-re2|, |im1-im2| <= precision on every amplitude
    (statevec_compareStates, QuEST_cpu.c)."""
    if qureg1.num_qubits_in_state_vec != qureg2.num_qubits_in_state_vec:
        return False
    _guard_host_gather(qureg1, "compareStates")
    a = np.asarray(qureg1.amps)
    b = np.asarray(qureg2.amps)
    return bool(np.all(np.abs(a - b) <= precision))
