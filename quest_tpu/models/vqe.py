"""VQE: variational quantum eigensolver — the framework's flagship "model".

The reference is a simulator library, so its "models" are user circuits; a
VQE is the canonical *training* workload built from its primitives
(parameterised ansatz + calcExpecPauliHamil, QuEST.h:4285).  Here the whole
VQE step — ansatz application, PauliHamil energy, gradient, Adam update —
is ONE jitted XLA program over the sharded state: something structurally
impossible in the reference (its gate-at-a-time dispatch has no autodiff
and no cross-gate fusion).

Sharding: the state is sharded over the mesh's ``amps`` axis (amplitude
sharding = the tensor-parallel analogue, SURVEY.md §2.2); a batch of
parameter sets can additionally be vmapped and sharded over a ``dp`` axis —
a genuine 2-D (dp, amps) mesh like an ML training job.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..env import AMP_AXIS
from ..ops import cplx, kernels, paulis


def _ry_soa(theta):
    c, s = jnp.cos(theta / 2), jnp.sin(theta / 2)
    re = jnp.stack([jnp.stack([c, -s]), jnp.stack([s, c])])
    return jnp.stack([re, jnp.zeros_like(re)])


def _rz_diag_soa(theta):
    c, s = jnp.cos(theta / 2), jnp.sin(theta / 2)
    return jnp.stack([jnp.stack([c, c]), jnp.stack([-s, s])])


class VQE:
    """Hardware-efficient ansatz (Ry+Rz layers with a CZ entangler chain)
    minimising <psi(theta)| H |psi(theta)> for a PauliHamil H."""

    def __init__(
        self,
        num_qubits: int,
        depth: int,
        hamil_codes: np.ndarray,
        hamil_coeffs: np.ndarray,
        mesh: Optional[Mesh] = None,
    ):
        self.num_qubits = int(num_qubits)
        self.depth = int(depth)
        self.codes_flat = tuple(int(c) for c in np.asarray(hamil_codes).ravel())
        self.num_terms = int(np.asarray(hamil_coeffs).size)
        self.coeffs = np.asarray(hamil_coeffs, dtype=np.float64)
        self.mesh = mesh

    @property
    def num_params(self) -> int:
        return 2 * self.num_qubits * self.depth

    def init_params(self, key) -> jax.Array:
        return 0.1 * jax.random.normal(key, (self.num_params,))

    # -- pure functions (jit/grad/vmap-safe) --

    def apply_ansatz(self, params):
        n = self.num_qubits
        amps = kernels.init_zero_state(1 << n, params.dtype)
        if self.mesh is not None:
            amps = lax.with_sharding_constraint(
                amps, NamedSharding(self.mesh, P(None, AMP_AXIS))
            )
        p = params.reshape(self.depth, 2, n)
        cz = cplx.soa(np.diag([1, 1, 1, -1]).astype(np.complex128))
        for layer in range(self.depth):
            for q in range(n):
                amps = kernels.apply_matrix(
                    amps, _ry_soa(p[layer, 0, q]), num_qubits=n, targets=(q,)
                )
                amps = kernels.apply_diagonal(
                    amps, _rz_diag_soa(p[layer, 1, q]), num_qubits=n, targets=(q,)
                )
            for q in range(n - 1):
                amps = kernels.apply_matrix(
                    amps, jnp.asarray(cz, params.dtype), num_qubits=n,
                    targets=(q, q + 1),
                )
        return amps

    def energy(self, params):
        amps = self.apply_ansatz(params)
        return paulis.calc_expec_pauli_sum_statevec(
            amps,
            jnp.asarray(self.coeffs, params.dtype),
            num_qubits=self.num_qubits,
            codes_flat=self.codes_flat,
            num_terms=self.num_terms,
        )

    def make_train_step(self, optimizer):
        """One fused (energy, grad, update) step; jit-compiled by caller."""

        def step(params, opt_state):
            e, grads = jax.value_and_grad(self.energy)(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            return params, opt_state, e

        return step


def random_hamiltonian(num_qubits: int, num_terms: int, seed: int = 0):
    """Random PauliHamil (codes, coeffs) for benchmarks/tests."""
    rng = np.random.default_rng(seed)
    codes = rng.integers(0, 4, size=(num_terms, num_qubits))
    coeffs = rng.standard_normal(num_terms)
    return codes, coeffs
