"""Whole-circuit builders: fuse many gates into ONE jitted XLA program.

The reference dispatches one kernel launch per gate (QuEST.c); tracing a
whole circuit lets XLA fuse adjacent elementwise/diagonal gates and
eliminate intermediate HBM round-trips — the main idiomatic performance win
of the TPU design (SURVEY.md §7 "fusion of gate sequences is free").

These functional circuits power the benchmarks (bench.py) and the example
models (Grover, Bernstein-Vazirani, QFT) and run on raw SoA amplitude
arrays; the imperative API remains available for gate-at-a-time use.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import cplx, gatedefs, kernels, paulis, phasefunc

_H_SOA = cplx.soa(gatedefs.HADAMARD)


def ghz_layer(amps, num_qubits: int):
    """H + CNOT chain."""
    amps = kernels.apply_matrix(amps, _H_SOA, num_qubits=num_qubits, targets=(0,))
    for t in range(1, num_qubits):
        amps = kernels.apply_multi_qubit_not(
            amps, num_qubits=num_qubits, targets=(t,), controls=(t - 1,)
        )
    return amps


def build_random_circuit(num_qubits: int, depth: int, seed: int = 0,
                         use_scan: bool = True):
    """Returns (fn, unitaries): fn(amps, unitaries) applies the whole
    depth-layer circuit as one traceable program.

    ``use_scan`` rolls the depth loop into ``lax.scan`` so the compiled
    program is one layer body regardless of depth (compiler-friendly
    control flow; the unrolled form is kept for fusion comparison)."""
    rng = np.random.default_rng(seed)
    us = np.empty((depth, num_qubits, 2, 2, 2))
    for d in range(depth):
        for q in range(num_qubits):
            m = _random_unitary_host(rng)
            us[d, q] = cplx.soa(m)
    unitaries = jnp.asarray(us, jnp.float32)

    n = num_qubits

    def _gates(amps, u_layer):
        for q in range(n):
            amps = kernels.apply_matrix(amps, u_layer[q], num_qubits=n, targets=(q,))
        return amps

    def _ladder(amps, offset: int):
        for q in range(offset, n - 1, 2):
            amps = kernels.apply_multi_qubit_not(
                amps, num_qubits=n, targets=(q + 1,), controls=(q,)
            )
        return amps

    if not use_scan:
        def fn(amps, unitaries):
            for d in range(depth):
                amps = _gates(amps, unitaries[d])
                amps = _ladder(amps, d % 2)
            return amps
        return fn, unitaries

    parities = jnp.arange(depth, dtype=jnp.int32) % 2

    def fn(amps, unitaries):
        def body(a, xs):
            u_layer, parity = xs
            a = _gates(a, u_layer)
            a = jax.lax.cond(
                parity == 0, lambda s: _ladder(s, 0), lambda s: _ladder(s, 1), a
            )
            return a, None

        amps, _ = jax.lax.scan(body, amps, (unitaries, parities))
        return amps

    return fn, unitaries


def _random_unitary_host(rng):
    a = rng.standard_normal((2, 2)) + 1j * rng.standard_normal((2, 2))
    q, r = np.linalg.qr(a)
    return q * (np.diag(r) / np.abs(np.diag(r)))


def qft_circuit(amps, num_qubits: int, layered: bool = False):
    """Full QFT as one traceable program.

    Default: circuit.fused_qft — one fused elementwise ladder pass per
    high layer (Hadamard + whole controlled-phase ladder), the low layers
    folded by the windowed scheduler, and the swap network collapsed to
    ONE bit-reversal axis permutation.

    ``layered=True`` (or n below the window size) uses the reference's
    per-layer strategy instead: H + SCALED_PRODUCT phase-ladder sweeps +
    pairwise swaps (agnostic_applyQFT, QuEST_common.c:836-898)."""
    n = num_qubits
    if not layered and n >= 14:
        from quest_tpu import circuit as CIRC

        return CIRC.fused_qft(amps, n, 0, n)
    empty_i = np.zeros((0, 2), np.int64)
    empty_p = np.zeros((0,), np.float64)
    for q in range(num_qubits - 1, -1, -1):
        amps = kernels.apply_matrix(amps, _H_SOA, num_qubits=num_qubits, targets=(q,))
        if q == 0:
            break
        params = np.array([math.pi / (1 << q), 0.0])
        amps = phasefunc.apply_named_phase_func(
            amps, params, empty_i, empty_p,
            num_qubits=num_qubits,
            reg_qubits=(tuple(range(q)), (q,)),
            encoding=phasefunc.UNSIGNED,
            func_name=phasefunc.SCALED_PRODUCT,
        )
    for i in range(num_qubits // 2):
        amps = kernels.swap_qubit_amps(
            amps, num_qubits=num_qubits, qb1=i, qb2=num_qubits - i - 1
        )
    return amps


def grover_circuit(num_qubits: int, marked: int, dtype=jnp.float32):
    """Grover search as one traceable program (reference example
    examples/grovers_search.c): optimal-iteration amplitude amplification.
    Prepares its own |+>^n start state."""
    n = num_qubits
    flip_marked = np.ones(1 << n)
    flip_marked[marked] = -1.0
    flip_zero = np.ones(1 << n)
    flip_zero[0] = -1.0
    d_marked = np.stack([flip_marked, np.zeros(1 << n)])
    d_zero = np.stack([flip_zero, np.zeros(1 << n)])

    amps = kernels.init_plus_state(1 << n, dtype)
    reps = max(1, int(round(math.pi / 4 * math.sqrt(2 ** n))))
    for _ in range(reps):
        # oracle: flip the marked amplitude
        amps = kernels.apply_diagonal(
            amps, d_marked, num_qubits=n, targets=tuple(range(n))
        )
        # diffusion: H^n . (flip |0>) . H^n
        for q in range(n):
            amps = kernels.apply_matrix(amps, _H_SOA, num_qubits=n, targets=(q,))
        amps = kernels.apply_diagonal(
            amps, d_zero, num_qubits=n, targets=tuple(range(n))
        )
        for q in range(n):
            amps = kernels.apply_matrix(amps, _H_SOA, num_qubits=n, targets=(q,))
    return amps


def bernstein_vazirani_circuit(num_qubits: int, secret: int, dtype=jnp.float32):
    """Bernstein-Vazirani (reference examples/bernstein_vazirani_circuit.c):
    finds `secret` with one oracle query.  Phase-oracle formulation: H^n,
    phase (-1)^{s.x}, H^n.  Prepares its own |+>^n start state."""
    n = num_qubits
    signs = np.array(
        [(-1.0) ** bin(i & secret).count("1") for i in range(1 << n)]
    )
    d_oracle = np.stack([signs, np.zeros(1 << n)])
    amps = kernels.init_plus_state(1 << n, dtype)
    amps = kernels.apply_diagonal(amps, d_oracle, num_qubits=n, targets=tuple(range(n)))
    for q in range(n):
        amps = kernels.apply_matrix(amps, _H_SOA, num_qubits=n, targets=(q,))
    return amps


# ---------------------------------------------------------------------------
# Benchmark-workload helpers shared by bench.py / scripts/bench_scale.py
# (BASELINE.json config 2 shape)
# ---------------------------------------------------------------------------

CNOT_SOA = np.zeros((2, 4, 4), np.float32)
CNOT_SOA[0] = np.array(
    [[1, 0, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0], [0, 1, 0, 0]], np.float32)


def bench_gate_list(num_qubits: int, depth: int, unitaries):
    """The config-2 gate list (per-layer 1q unitaries + alternating CNOT
    ladder) as circuit.Gate objects, for the windowed planner.  CNOT
    convention: control = matrix bit 0 (= targets[0]), target = bit 1."""
    from .. import circuit as C

    gates = []
    for d in range(depth):
        for q in range(num_qubits):
            gates.append(C.Gate((q,), unitaries[d, q]))
        for q in range(d % 2, num_qubits - 1, 2):
            gates.append(C.Gate((q, q + 1), CNOT_SOA))
    return gates


def zero_state_canonical(num_qubits: int):
    """|0...0> directly in the canonical (2, nb, 128, 128) tiled view,
    built inside ONE jitted program (an eager zeros + scatter transiently
    holds two full states — an OOM at 30q)."""
    return _zero_state_canonical_jit(n=num_qubits)


@partial(jax.jit, static_argnames=("n",))
def _zero_state_canonical_jit(*, n):
    nb = 1 << (n - 14)
    return jnp.zeros((2, nb, 128, 128), jnp.float32).at[0, 0, 0, 0].set(1.0)


@jax.jit
def prob_top_zero_canonical(a):
    """P(top qubit = 0) on the canonical view: a contiguous half-slice
    sum — layout-preserving (calc_prob's generic reshape would re-tile
    the canonical layout into an 8 GB temp at 30q).  Needs n >= 15 so
    the top qubit is a whole slice of the tile axis."""
    if a.shape[1] < 2:
        raise ValueError("prob_top_zero_canonical needs >= 2 tiles (n >= 15)")
    h = a[:, : a.shape[1] // 2]
    return jnp.sum(h * h)


@jax.jit
def amp00_canonical(a):
    """Layout-preserving scalar sync on the canonical view (a gather-style
    a[0,0,0,0] makes XLA relayout the whole state)."""
    return jnp.sum(a[:1, :1, :1, :1])
