"""QAOA for MaxCut — second training "model family" on the simulator.

Like VQE (models/vqe.py), this is a workload the reference can only
evaluate piecewise (diagonal phases via applyPhaseFunc, mixers via
rotateX, expectation via calcExpecDiagonalOp — QuEST.h:5571,2220,1255)
with no autodiff; here the full QAOA step is one differentiable jitted
program.

TPU fit: the cost layer e^{-i gamma C} for a diagonal cost C is a pure
elementwise multiply (no amplitude pairing at all), and the cost
expectation is an elementwise reduce — both stream at HBM bandwidth. The
cost vector is built lazily in-graph from iota bit arithmetic, so no
host-side 2^n table is materialized or transferred.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..env import AMP_AXIS
from ..ops import kernels


class QAOA:
    """p-layer QAOA minimising the MaxCut cost C(z) = sum_e w_e [z_i != z_j]
    (maximising the cut) over ``edges`` = [(i, j, w), ...]."""

    def __init__(
        self,
        num_qubits: int,
        edges: Sequence[Tuple[int, int, float]],
        depth: int,
        mesh: Optional[Mesh] = None,
    ):
        self.num_qubits = int(num_qubits)
        self.edges = tuple((int(i), int(j), float(w)) for i, j, w in edges)
        self.depth = int(depth)
        self.mesh = mesh

    @property
    def num_params(self) -> int:
        return 2 * self.depth  # (gamma, beta) per layer

    def init_params(self, key) -> jax.Array:
        return 0.1 * jax.random.normal(key, (self.num_params,))

    def _cost_2d(self, dtype):
        """Cut-size c(z) as a (2^hi, 2^lo) array built from iota bit views
        (kernels.bit_2d: XLA fuses the per-edge XOR chain into the consuming
        multiply; no host-side 2^n table and no high-rank broadcast)."""
        n = self.num_qubits
        c = jnp.zeros((1, 1), dtype=dtype)
        for i, j, w in self.edges:
            c = c + w * (kernels.bit_2d(n, i) ^ kernels.bit_2d(n, j)).astype(dtype)
        return c

    def state(self, params):
        """|psi(gamma, beta)> after p alternating cost/mixer layers."""
        n = self.num_qubits
        amps = kernels.init_plus_state(1 << n, params.dtype)
        if self.mesh is not None:
            amps = lax.with_sharding_constraint(
                amps, NamedSharding(self.mesh, P(None, AMP_AXIS))
            )
        cost = self._cost_2d(params.dtype)
        hi, lo = kernels._split2(n)
        p = params.reshape(self.depth, 2)
        for layer in range(self.depth):
            gamma, beta = p[layer, 0], p[layer, 1]
            # cost phase: elementwise exp(-i gamma c(z))
            view = amps.reshape(2, 1 << hi, 1 << lo)
            ang = -gamma * cost
            re = view[0] * jnp.cos(ang) - view[1] * jnp.sin(ang)
            im = view[0] * jnp.sin(ang) + view[1] * jnp.cos(ang)
            amps = jnp.stack([re, im]).reshape(2, -1)
            # mixer: RX(2 beta) on every qubit
            cb, sb = jnp.cos(beta), jnp.sin(beta)
            rx = jnp.stack([
                jnp.stack([jnp.stack([cb, jnp.zeros_like(cb)]),
                           jnp.stack([jnp.zeros_like(cb), cb])]),
                jnp.stack([jnp.stack([jnp.zeros_like(sb), -sb]),
                           jnp.stack([-sb, jnp.zeros_like(sb)])]),
            ])  # SoA (2,2,2): cos(b) I - i sin(b) X
            for q in range(n):
                amps = kernels.apply_matrix(amps, rx, num_qubits=n, targets=(q,))
        return amps

    def expected_cut(self, params):
        """<psi| C |psi> — the quantity QAOA maximises."""
        amps = self.state(params)
        cost = self._cost_2d(params.dtype)
        hi, lo = kernels._split2(self.num_qubits)
        view = amps.reshape(2, 1 << hi, 1 << lo)
        probs = view[0] * view[0] + view[1] * view[1]
        return jnp.sum(probs * cost)

    def loss(self, params):
        return -self.expected_cut(params)

    def make_train_step(self, optimizer):
        def step(params, opt_state):
            neg_cut, grads = jax.value_and_grad(self.loss)(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda a, u: a + u, params, updates)
            return params, opt_state, -neg_cut

        return step


def random_graph(num_qubits: int, num_edges: int, seed: int = 0):
    """Random weighted graph for tests/benchmarks."""
    rng = np.random.default_rng(seed)
    edges = set()
    while len(edges) < num_edges:
        i, j = rng.integers(0, num_qubits, 2)
        if i != j:
            edges.add((min(i, j), max(i, j)))
    return [(i, j, float(rng.uniform(0.5, 1.5))) for i, j in sorted(edges)]
