"""Persistent AOT executable cache (docs/design.md §31).

BENCH_r03: 41 s compile+first-run against a 65 ms steady-state drain —
at serving scale interactive p99 is compile-bound, not execution-bound.
This module eliminates the cold start by serializing compiled fusion
runners (``jax.experimental.serialize_executable``) to a content-hashed
on-disk cache keyed by the FULL semantic identity the plan layer
already computes, so a fresh process (or a fresh replica, or the
shrunk-mesh executor a failover restores onto) pays a millisecond
deserialize instead of a multi-second XLA compile.

Key schema (``runner_key``) — every knob that changes the compiled
artifact must appear here; anything missing is a silent wrong-answer
bug, anything extra is a silent cache miss:

  - toolchain: jax / jaxlib version + backend platform (a jax upgrade
    invalidates everything; ``_VERSION_OVERRIDE`` lets tests spoof it)
  - program identity: ``nloc`` + the planned program skeleton (which
    already folds the structure fingerprint, window split, megakernel
    grouping, permutation fast paths, and optimizer rewrite)
  - mesh identity: axis names/sizes, device kind, Topology.signature()
  - dispatch knobs: matmul precision, exchange-chunks key, batch mode,
    optimizer mode, QT_MEGAKERNEL planning flag, QT_PERM_FAST
  - argument signature: aval (shape, dtype, weak_type) of every operand

File format: ``b"QTAOT1\\n" + sha256(body) + body`` where body is a
pickle of ``{v, key, payload, in_tree, out_tree, meta}``.  Writes are
atomic (tempfile + os.replace in the cache dir); loads verify magic,
checksum, and key echo — any mismatch counts an error, records a
degradation, unlinks the bad entry, and falls back to a fresh compile
(bit-identical results either way; the cache is an accelerator, never
a correctness dependency).  Eviction is mtime-LRU against
``QT_AOT_CACHE_MAX_BYTES`` (default 1 GiB); hits ``os.utime`` the
entry so the hot set survives.

Enabled by ``QT_AOT_CACHE=<dir>``; with it unset ``wrap_runner``
returns the jitted runner untouched (zero overhead on the default
path).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
import threading
import time
from typing import Optional

import jax
import numpy as np

from . import telemetry as _telemetry

__all__ = [
    "enabled", "cache_dir", "max_bytes", "runner_key", "load", "store",
    "wrap_runner", "probe", "stats", "amps_struct", "arg_sig",
]

_DIR_ENV = "QT_AOT_CACHE"
_MAX_BYTES_ENV = "QT_AOT_CACHE_MAX_BYTES"
_DEFAULT_MAX_BYTES = 1 << 30  # 1 GiB
_MAGIC = b"QTAOT1\n"
_SUFFIX = ".aot"

# Spoofable toolchain tag: tests set _VERSION_OVERRIDE[0] to prove a
# jax upgrade invalidates every entry without actually upgrading jax.
_VERSION_OVERRIDE: list = [None]

_LOCK = threading.Lock()

# Keys whose executable is live in THIS process (wrapper dict or
# prewarm) — the explain predictor reports these as "memory": the next
# drain will not consult the disk tier at all.
_MEMORY_KEYS: set = set()

# Process-wide cache-tier accounting.  Deliberately a plain dict (the
# env._CACHE_STATS idiom) rather than telemetry counters: the AOT tier
# must account even with QT_TELEMETRY=off, and telemetry._series()
# folds these in so the consolidated block distinguishes the two cache
# tiers (ISSUE 20 satellite 6).
_STATS = {
    "hits": 0, "misses": 0, "puts": 0, "evictions": 0, "errors": 0,
    "bytes": 0, "saved_seconds": 0.0,
}


def reset_stats() -> None:
    """Test hook: zero the process-wide stats and the in-memory key set
    (simulates a fresh process for hit/miss pinning)."""
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0.0 if k == "saved_seconds" else 0
        _MEMORY_KEYS.clear()


def cache_dir() -> Optional[str]:
    d = os.environ.get(_DIR_ENV, "").strip()
    return d or None


def enabled() -> bool:
    return cache_dir() is not None


def max_bytes() -> int:
    try:
        return int(os.environ.get(_MAX_BYTES_ENV, str(_DEFAULT_MAX_BYTES)))
    except ValueError:
        return _DEFAULT_MAX_BYTES


def _version_tag() -> tuple:
    if _VERSION_OVERRIDE[0] is not None:
        return ("override", str(_VERSION_OVERRIDE[0]))
    try:
        import jaxlib

        jl = getattr(jaxlib, "__version__", "?")
    # qlint: allow(broad-except): jaxlib is an implementation detail of the jax install — any import/attr surprise degrades the tag component to "?" rather than disabling the cache
    except Exception:
        jl = "?"
    return (jax.__version__, jl, jax.default_backend())


def _mesh_tag(mesh) -> Optional[tuple]:
    """Portable mesh identity: axis layout + device kind + topology
    signature.  Deliberately NOT the Mesh object — a failover builds a
    fresh Mesh over the surviving devices, and the prewarmed shrunk-mesh
    entry must still hit."""
    if mesh is None:
        return None
    devs = np.asarray(mesh.devices).reshape(-1)
    try:
        kind = str(devs[0].device_kind)
    # qlint: allow(broad-except): device_kind is backend-dependent metadata — any failure degrades the key to "?" (still a valid, stable tag) instead of breaking dispatch
    except Exception:
        kind = "?"
    from .parallel import topology as _topo

    return (tuple(str(a) for a in mesh.axis_names),
            tuple(int(s) for s in np.asarray(mesh.devices).shape),
            kind, _topo.signature(int(devs.size)))


def _aval_of(x) -> tuple:
    """(shape, dtype, weak_type) signature of one runner operand —
    identical for a live concrete array, a numpy array, a Python float
    (weak-typed scalar), and the ShapeDtypeStruct a prewarm passes."""
    if isinstance(x, jax.ShapeDtypeStruct):
        return (tuple(x.shape), str(x.dtype),
                bool(getattr(x, "weak_type", False)))
    aval = jax.core.get_aval(x)
    return (tuple(aval.shape), str(aval.dtype),
            bool(getattr(aval, "weak_type", False)))


def arg_sig(amps, arrays, probs) -> tuple:
    return ((_aval_of(amps),)
            + tuple(_aval_of(a) for a in arrays)
            + tuple(_aval_of(p) for p in probs))


def runner_key(nloc: int, program, mesh, precision, exchange_key,
               batch: int, sig: tuple) -> str:
    """sha256 hex over the full semantic identity of one compiled
    fusion runner (module docstring: the invalidation matrix)."""
    from . import circuit as _C
    from . import optimizer as _opt
    from .ops import fused as _fused

    parts = (
        "qt-aot-v1", _version_tag(), int(nloc), int(batch),
        str(precision), str(exchange_key), _mesh_tag(mesh),
        str(_opt.mode()), bool(_C.perm_fast_enabled()),
        bool(_fused.megakernel_planning()), repr(program), sig,
    )
    return hashlib.sha256(repr(parts).encode()).hexdigest()


def _path(key: str) -> str:
    return os.path.join(cache_dir(), key + _SUFFIX)


def _bump(name: str, by=1) -> None:
    with _LOCK:
        _STATS[name] += by


def _record_corrupt(path: str, why: str) -> None:
    _bump("errors")
    try:
        os.remove(path)
    except OSError:
        pass
    try:
        from . import resilience as _res

        _res.record_degradation(
            "aot_cache_corrupt",
            "AOT cache entry %s rejected (%s); fell back to a fresh "
            "compile — results are unaffected" % (
                os.path.basename(path), why))
    # qlint: allow(broad-except): recording the degradation is best-effort observability — the corruption fallback itself must complete even mid-teardown
    except Exception:
        pass
    _refresh_bytes()


def _scan() -> list:
    """[(path, size, mtime)] for every entry in the cache dir."""
    d = cache_dir()
    out = []
    if not d or not os.path.isdir(d):
        return out
    for name in os.listdir(d):
        if not name.endswith(_SUFFIX):
            continue
        p = os.path.join(d, name)
        try:
            st = os.stat(p)
        except OSError:
            continue
        out.append((p, st.st_size, st.st_mtime))
    return out

def _refresh_bytes() -> int:
    total = sum(sz for _p, sz, _m in _scan())
    with _LOCK:
        _STATS["bytes"] = total
    if _telemetry.enabled():
        _telemetry.set_gauge("aot_cache_bytes", float(total))
    return total


def _evict() -> None:
    """mtime-LRU eviction down to QT_AOT_CACHE_MAX_BYTES."""
    cap = max_bytes()
    entries = sorted(_scan(), key=lambda e: e[2])  # oldest first
    total = sum(sz for _p, sz, _m in entries)
    for p, sz, _m in entries:
        if total <= cap:
            break
        try:
            os.remove(p)
        except OSError:
            continue
        total -= sz
        _bump("evictions")
        if _telemetry.enabled():
            _telemetry.inc("aot_cache_evictions_total")
    with _LOCK:
        _STATS["bytes"] = total
    if _telemetry.enabled():
        _telemetry.set_gauge("aot_cache_bytes", float(total))


def load(key: str):
    """Consult the disk tier.  Returns (compiled, meta) on a verified
    hit, None on a miss; corruption of any flavour is a counted miss
    with a degradation record and the bad entry unlinked."""
    if not enabled():
        return None
    path = _path(key)
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except FileNotFoundError:
        _bump("misses")
        if _telemetry.enabled():
            _telemetry.inc("aot_cache_misses_total")
        return None
    except OSError as e:
        _record_corrupt(path, "unreadable: %s" % e)
        _bump("misses")
        if _telemetry.enabled():
            _telemetry.inc("aot_cache_misses_total")
        return None
    try:
        if blob[:len(_MAGIC)] != _MAGIC:
            raise ValueError("bad magic")
        off = len(_MAGIC)
        digest, body = blob[off:off + 32], blob[off + 32:]
        if hashlib.sha256(body).digest() != digest:
            raise ValueError("checksum mismatch")
        ent = pickle.loads(body)
        if ent.get("v") != 1 or ent.get("key") != key:
            raise ValueError("key/version mismatch")
        from jax.experimental.serialize_executable import (
            deserialize_and_load)

        compiled = deserialize_and_load(
            ent["payload"], ent["in_tree"], ent["out_tree"])
    # qlint: allow(broad-except): the corruption-safe fallback contract — a truncated/tampered/stale entry may fail anywhere in unpickle/deserialize, and every failure mode must degrade to a fresh compile
    except Exception as e:
        _record_corrupt(path, str(e) or type(e).__name__)
        _bump("misses")
        if _telemetry.enabled():
            _telemetry.inc("aot_cache_misses_total")
        return None
    meta = ent.get("meta") or {}
    saved = float(meta.get("compile_seconds", 0.0))
    _bump("hits")
    _bump("saved_seconds", saved)
    if _telemetry.enabled():
        _telemetry.inc("aot_cache_hits_total")
        if saved:
            _telemetry.inc("aot_compile_seconds_saved_total", saved)
    try:
        os.utime(path)  # refresh LRU position
    except OSError:
        pass
    return compiled, meta


def store(key: str, compiled, compile_seconds: float, meta=None) -> bool:
    """Persist one compiled executable (atomic tempfile + os.replace),
    then evict down to the byte cap.  Best-effort: any failure counts
    an error and the caller keeps its in-memory executable."""
    d = cache_dir()
    if d is None:
        return False
    try:
        from jax.experimental.serialize_executable import serialize

        payload, in_tree, out_tree = serialize(compiled)
        ent = {
            "v": 1, "key": key, "payload": payload,
            "in_tree": in_tree, "out_tree": out_tree,
            "meta": dict(meta or {},
                         compile_seconds=float(compile_seconds),
                         version=_version_tag()),
        }
        body = pickle.dumps(ent, protocol=pickle.HIGHEST_PROTOCOL)
        blob = _MAGIC + hashlib.sha256(body).digest() + body
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, _path(key))
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
    # qlint: allow(broad-except): persistence is an accelerator, never a dependency — serialize-unsupported backends, full disks, and permission errors all leave the caller its in-memory executable
    except Exception:
        _bump("errors")
        return False
    _bump("puts")
    if _telemetry.enabled():
        _telemetry.inc("aot_cache_puts_total")
    _evict()
    return True


def stats() -> dict:
    with _LOCK:
        out = dict(_STATS)
    out["enabled"] = enabled()
    out["dir"] = cache_dir()
    out["memory_keys"] = len(_MEMORY_KEYS)
    return out


def amps_struct(num_amps: int, batch: int, dtype, mesh):
    """ShapeDtypeStruct standing in for a register's ``_amps`` operand —
    the SAME aval (shape, dtype, sharding) a live drain dispatches, so
    a prewarm from analytic shapes produces the key and executable the
    live request then hits."""
    shape = (batch, 2, num_amps) if batch else (2, num_amps)
    sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from .env import AMP_AXIS

        spec = P(None, None, AMP_AXIS) if batch else P(None, AMP_AXIS)
        sharding = NamedSharding(mesh, spec)
    return jax.ShapeDtypeStruct(shape, np.dtype(dtype), sharding=sharding)


def probe(nloc: int, program, mesh, precision, exchange_key, batch: int,
          sig: tuple) -> dict:
    """Side-effect-free hit/miss prediction for explainCircuit: computes
    the key the next drain would use and reports where its executable
    currently lives.  ``memory`` = a live in-process executable (the
    disk tier will not be consulted); ``hit`` / ``miss`` = the disk
    tier's answer for a fresh executor."""
    if not enabled():
        return {"enabled": False, "status": "disabled", "key": None}
    if not program:
        return {"enabled": True, "status": "uncacheable", "key": None}
    key = runner_key(nloc, program, mesh, precision, exchange_key,
                     batch, sig)
    with _LOCK:
        in_mem = key in _MEMORY_KEYS
    if in_mem:
        status = "memory"
    elif os.path.exists(_path(key)):
        status = "hit"
    else:
        status = "miss"
    return {"enabled": True, "status": status, "key": key}


def wrap_runner(run, *, nloc: int, program, mesh, precision,
                exchange_key, batch: int):
    """Wrap one freshly-traced fusion runner with the AOT tier.

    Disabled (no QT_AOT_CACHE): returns ``run`` untouched.  Enabled:
    returns a drop-in callable that, per argument signature,
    consults-before-compile (disk hit -> deserialize) and
    persists-on-miss (``run.lower(...).compile()`` timed + stored),
    then dispatches the compiled executable directly.  Tracer operands
    (a drain reached from inside a user jit) fall through to the plain
    jit, as does ANY failure in the cache path before execution —
    the cache never gates correctness.

    The wrapper carries a ``.prewarm(amps_spec, arrays, probs)``
    attribute: load-or-compile from analytic ShapeDtypeStructs WITHOUT
    executing — the serve-layer warm pool's entry point.  A
    threading.Lock serializes the prewarmer thread against the live
    scheduler so a racing first request cannot double-compile."""
    if not enabled():
        return run

    compiled_by_sig: dict = {}
    lock = threading.Lock()
    first = [True]

    def _materialize(sig, args):
        """Disk-load or fresh-compile the executable for ``sig``.
        Returns (compiled, from_cache); caller holds ``lock``."""
        key = runner_key(nloc, program, mesh, precision, exchange_key,
                         batch, sig)
        got = load(key)
        if got is not None:
            compiled = got[0]
            from_cache = True
        else:
            t0 = time.perf_counter()
            compiled = run.lower(*args).compile()
            store(key, compiled, time.perf_counter() - t0)
            from_cache = False
        compiled_by_sig[sig] = compiled
        with _LOCK:
            _MEMORY_KEYS.add(key)
        return compiled, from_cache

    def wrapped(amps, arrays, probs):
        if isinstance(amps, jax.core.Tracer):
            return run(amps, arrays, probs)
        t0 = time.perf_counter()
        try:
            sig = arg_sig(amps, arrays, probs)
            with lock:
                compiled = compiled_by_sig.get(sig)
                if compiled is None:
                    compiled, from_cache = _materialize(
                        sig, (amps, arrays, probs))
                else:
                    from_cache = True  # warm: memory tier (or prewarm)
        # qlint: allow(broad-except): any cache-path failure BEFORE execution falls back to the plain jit — the donated operand is untouched, results identical
        except Exception:
            return run(amps, arrays, probs)
        out = compiled(amps, arrays, probs)
        if first[0]:
            first[0] = False
            if _telemetry.enabled():
                jax.block_until_ready(out)
                _telemetry.observe(
                    "first_request_seconds", time.perf_counter() - t0,
                    fingerprint_cached="true" if from_cache else "false")
        return out

    def prewarm(amps_spec, arrays, probs):
        """Load-or-compile without executing.  Returns ``"present"``
        (already live), ``"hit"`` (deserialized from disk), or
        ``"compiled"`` (fresh AOT compile, persisted)."""
        sig = arg_sig(amps_spec, arrays, probs)
        with lock:
            if sig in compiled_by_sig:
                return "present"
            _c, from_cache = _materialize(sig, (amps_spec, arrays, probs))
        return "hit" if from_cache else "compiled"

    wrapped.prewarm = prewarm
    wrapped.aot_wrapped = True
    return wrapped
