"""Checkpoint / resume: durable snapshots of registers and operators.

The reference's persistence story is minimal — a per-rank CSV dump
(reportState, QuEST_common.c:229-245), a debug-only CSV loader
(initStateFromSingleFile, QuEST_cpu.c:1680-1729) and amplitude get/set
APIs users must script themselves (SURVEY.md §5.4).  This module exceeds
that: orbax-backed save/restore of the (possibly sharded) amplitude array
with metadata, so a multi-device register round-trips with its sharding
reconstructed on the current mesh — plus CSV read/write kept for
reference-format compatibility.
"""

from __future__ import annotations

import json
import math
import os

import jax
import numpy as np

from .env import QuESTEnv
from .qureg import Qureg
from .validation import QuESTError

_META_NAME = "qureg_meta.json"
_AMPS_NAME = "amps"


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _qureg_meta(qureg: Qureg) -> dict:
    """Base register metadata (the resilience layer extends it with a
    circuit cursor, the live permutation, and the RNG state)."""
    from . import precision

    return {
        "num_qubits_represented": qureg.num_qubits_represented,
        "is_density_matrix": qureg.is_density_matrix,
        "dtype": str(np.dtype(qureg.dtype)),
        "precision": precision.get_precision(),
        "mesh_shards": qureg.num_chunks,
        # 0 = scalar register; B >= 1 = a BatchedQureg bank of B elements
        # (batch.py) whose payload is (B, 2, 2^n)
        "batch": int(getattr(qureg, "batch_size", 0) or 0),
    }


def _write_meta(path: str, meta: dict) -> None:
    tmp = os.path.join(path, _META_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(path, _META_NAME))


def _read_meta(path: str) -> dict:
    meta_path = os.path.join(path, _META_NAME)
    if not os.path.exists(meta_path):
        raise FileNotFoundError(f"no qureg checkpoint at {path}")
    with open(meta_path) as f:
        meta = json.load(f)
    if not isinstance(meta, dict) or "num_qubits_represented" not in meta:
        raise ValueError(f"malformed checkpoint metadata at {meta_path}")
    return meta


def _qureg_from_meta(meta: dict, env: QuESTEnv) -> Qureg:
    """Build the target register for a restore, validating the checkpoint
    against THIS env up front — a precision or shardability mismatch must
    surface as a structured QuESTError naming both sides, not as an orbax
    resharding failure deep inside the restore."""
    from . import precision

    ck_dtype = np.dtype(meta["dtype"])
    env_dtype = precision.real_dtype()
    if ck_dtype != np.dtype(env_dtype):
        raise QuESTError(
            "loadQureg: checkpoint precision mismatch — the checkpoint "
            f"was written at dtype {ck_dtype} (precision "
            f"{meta.get('precision', '?')}) but this environment runs at "
            f"dtype {np.dtype(env_dtype)} (precision "
            f"{precision.get_precision()}); call set_precision to match "
            "before loading"
        )
    batch = int(meta.get("batch", 0) or 0)
    if batch:
        from .batch import BatchedQureg

        q = BatchedQureg(meta["num_qubits_represented"], env, batch,
                         is_density_matrix=meta["is_density_matrix"])
    else:
        q = Qureg(meta["num_qubits_represented"], env,
                  meta["is_density_matrix"])
    if q.num_amps_total < env.num_devices:
        raise QuESTError(
            "loadQureg: the mesh has grown past the register's shardable "
            f"size — the checkpoint holds {q.num_amps_total} amplitudes "
            f"({meta['num_qubits_represented']} qubits, density="
            f"{meta['is_density_matrix']}) but this environment has "
            f"{env.num_devices} devices; load on a mesh with at most "
            f"{q.num_amps_total} devices"
        )
    q.dtype = ck_dtype
    return q


def _restore_amps(path: str, q: Qureg):
    """Restore the amplitude payload for ``q`` from ``path`` (transient IO
    errors retried with bounded exponential backoff)."""
    from . import resilience

    ckpt = _checkpointer()
    batch = int(getattr(q, "batch_size", 0) or 0)
    shape = (batch, 2, q.num_amps_total) if batch else (2, q.num_amps_total)
    target = jax.ShapeDtypeStruct(shape, q.dtype, sharding=q.sharding())
    restored = resilience.retry_io(
        ckpt.restore, os.path.join(path, _AMPS_NAME), {"amps": target},
        what="loadQureg(amps)")
    return restored["amps"]


def saveQureg(qureg: Qureg, path: str) -> None:
    """Write a durable snapshot of ``qureg`` (amps + metadata) at ``path``.

    Works for state-vectors and density matrices, any sharding; the write
    is atomic at the directory level (orbax finalization), and transient
    IO errors are retried with bounded exponential backoff
    (resilience.retry_io).  Amplitudes are written in CANONICAL qubit
    order (any live permutation rematerializes first); the resilience
    layer's generation protocol (resilience.save_generation) instead
    snapshots the raw permuted state for bit-exact mid-circuit resume."""
    from . import resilience

    path = os.path.abspath(path)
    ckpt = _checkpointer()
    resilience.retry_io(
        ckpt.save, os.path.join(path, _AMPS_NAME), {"amps": qureg.amps},
        force=True, what="saveQureg(amps)")
    resilience.retry_io(ckpt.wait_until_finished, what="saveQureg(wait)")
    resilience.retry_io(_write_meta, path, _qureg_meta(qureg),
                        what="saveQureg(meta)")


def loadQureg(path: str, env: QuESTEnv, *, strict_mesh: bool = False) -> Qureg:
    """Restore a register saved by :func:`saveQureg` onto ``env``'s mesh.

    The amplitude array is restored directly into the register's current
    sharding (resharding on the fly if the mesh shape changed).  The
    checkpoint metadata is validated against ``env`` FIRST: a precision
    mismatch (e.g. written at prec 2, loaded at prec 1) raises a
    QuESTError naming both sides instead of failing inside orbax
    resharding.

    When the mesh has grown past the register's shardable size (more
    devices than amplitudes), the default is ELASTIC: the environment
    auto-shrinks to the largest usable device subset (env.shrink_env,
    recorded in the degradation registry) and the register loads onto
    that degraded mesh — its ``env`` attribute names the shrunken
    environment.  ``strict_mesh=True`` restores the old structured
    error, and additionally refuses ANY shard-count difference from the
    writing mesh (recorded in the checkpoint metadata)."""
    from . import resilience, telemetry

    path = os.path.abspath(path)
    try:
        meta = _read_meta(path)
    except FileNotFoundError:
        raise QuESTError(f"no qureg checkpoint at {path}", "loadQureg")
    saved_shards = meta.get("mesh_shards")
    if strict_mesh and saved_shards is not None \
            and int(saved_shards) != env.num_devices:
        raise QuESTError(
            "loadQureg: checkpoint mesh mismatch — written on "
            f"{saved_shards} shards but this environment has "
            f"{env.num_devices} devices, and strict_mesh=True refuses "
            "elastic restore")
    n_sv = (2 if meta.get("is_density_matrix") else 1) \
        * int(meta["num_qubits_represented"])
    total = 1 << n_sv
    if not strict_mesh and total < env.num_devices:
        from . import env as _env_mod

        shrunk = _env_mod.shrink_env(env, total)
        resilience.record_degradation(
            f"loadQureg_mesh_{env.num_devices}to{total}",
            f"the mesh ({env.num_devices} devices) has grown past the "
            f"register's shardable size ({total} amplitudes); loaded "
            f"onto a {total}-device sub-mesh")
        env = shrunk
    if saved_shards is not None and int(saved_shards) != env.num_devices:
        telemetry.inc("elastic_restores_total")
    q = _qureg_from_meta(meta, env)
    q.amps = _restore_amps(path, q)
    return q


# ---------------------------------------------------------------------------
# Reference-format CSV ("re, im" per line, '#' comments) — the format
# reportState writes and initStateFromSingleFile reads in the reference.
# ---------------------------------------------------------------------------


def writeStateToFile(qureg: Qureg, filename: str) -> None:
    """Dump amplitudes as reference-style CSV (QuEST_common.c:229-245).

    Streams tile-aligned 2^14-amp blocks to disk (element.get_block_host)
    instead of gathering the whole state into one host buffer, matching
    the reference's per-rank chunked reportState — so large states keep
    CSV export with no max_amps_in_msg cap (ADVICE r4)."""
    from .ops import element

    total = qureg.num_amps_total
    amps = qureg.amps
    if amps.ndim != 4 and amps.shape[1] >= element.BLK:
        # canonical 4-d view first: a raw flat block offset overflows
        # int32 at >= 2^31 amps in x64-off mode (element.py:_as_canonical)
        amps = element._as_canonical(amps)
    # fetch in multi-block chunks: one device->host round-trip costs
    # ~100 ms through the relay, so per-2^14-block fetches would take
    # hours at 2^30 amps; 2^10 blocks (2^24 amps, ~128-256 MB host)
    # keeps memory bounded while cutting the fetch count ~1000x
    chunk_blocks = 1 << 10
    with open(filename, "w") as f:
        f.write("# quest_tpu state dump: re, im per amplitude\n")
        written = 0
        nblocks = (total + element.BLK - 1) // element.BLK
        for b0 in range(0, nblocks, chunk_blocks):
            nb = min(chunk_blocks, nblocks - b0)
            if amps.ndim == 4:
                part = np.asarray(jax.lax.dynamic_slice_in_dim(
                    amps, b0, nb, axis=1)).reshape(2, -1)
            else:
                part = np.asarray(jax.lax.dynamic_slice(
                    amps, (0, b0 * element.BLK),
                    (2, min(nb * element.BLK, amps.shape[1]))))
            m = min(part.shape[1], total - written)
            for k in range(m):
                f.write(f"{float(part[0, k])!r}, {float(part[1, k])!r}\n")
            written += m


# amps per streamed read chunk: 2^20 f64 pairs = 16 MB host buffer, and
# each chunk is one tile-aligned ranged write (element.set_amp_range)
_READ_CHUNK = 1 << 20


def readStateFromFile(qureg: Qureg, filename: str) -> bool:
    """Load amplitudes from reference-style CSV; returns success
    (statevec_initStateFromSingleFile, QuEST_cpu.c:1680-1729).

    Streams the file in tile-aligned chunks through ranged device writes
    (element.set_amp_range) into a fresh device-side buffer — the
    register is only rebound on full success, so failure semantics are
    unchanged (malformed/truncated/garbage file leaves the state
    untouched — the stream writes into a fresh device buffer, never the
    live register).  Non-finite values (NaN/Inf — a torn write or bit
    rot, never a legal amplitude) are rejected like any other parse
    failure.  No full-state host buffer is ever built, restoring
    round-trip symmetry with the streamed writeStateToFile: any state
    that module can dump, this can load (the old path hard-failed via
    _guard_host_gather beyond the message cap — ADVICE r5)."""
    import jax.numpy as jnp

    from .ops import element

    if not os.path.exists(filename):
        return False
    total = qureg.num_amps_total
    work = jax.device_put(
        jnp.zeros((2, total), qureg.dtype), qureg.sharding())
    buf = np.zeros((2, _READ_CHUNK))
    fill = 0          # valid amps in buf
    written = 0       # amps flushed to the device
    try:
        with open(filename) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if written + fill >= total:
                    break
                parts = line.split(",")
                re, im = float(parts[0]), float(parts[1])
                if not (math.isfinite(re) and math.isfinite(im)):
                    return False
                buf[0, fill], buf[1, fill] = re, im
                fill += 1
                if fill == _READ_CHUNK:
                    work = element.set_amp_range(work, written,
                                                 buf.astype(qureg.dtype))
                    written += fill
                    fill = 0
    except (ValueError, IndexError):
        return False  # malformed line: report failure, leave state untouched
    if fill:
        work = element.set_amp_range(work, written,
                                     buf[:, :fill].astype(qureg.dtype))
        written += fill
    if written < total:
        return False  # truncated file
    qureg.amps = work
    return True
