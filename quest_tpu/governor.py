"""Memory-governed execution: HBM budgeting, admission, spill, OOM net.

The reference validates per-rank memory once, at register creation
(QuEST validateMemoryAllocationSize) and then trusts the allocator;
everything after that is an abort.  On TPU the failure mode is worse:
XLA's ``RESOURCE_EXHAUSTED`` kills the process mid-drain, after the
donated input buffer may already be gone (the incidents recorded at
circuit.py "round-2 OOM that blocked 30q" and fusion.py "+1.25 GiB PER
CHANNEL at 13q rho -> 21 GiB OOM").  This module turns memory into an
admission decision the way an inference server gates requests on a
KV-cache budget (docs/design.md §22):

* **Budget** — per-device HBM bytes, from ``Device.memory_stats()``
  (``bytes_limit``) with a ``QT_HBM_BUDGET_BYTES`` override so the
  8-shard CPU dryrun is fully testable.  ``QT_MEM_POLICY`` selects
  ``off`` / ``degrade`` (default) / ``strict``.  With no budget (the
  bare CPU backend) the governor is inert and every path below is a
  cheap no-op.

* **Ledger** — every live register is tracked (weakly) with its modeled
  per-device footprint and an LRU tick, so "available" is always
  budget minus resident bytes, and spill candidates come out in
  least-recently-used order.

* **Predictor** — the analytic peak of a planned drain:
  ``state_shard_bytes x (1 + max part extra) + pass-array bytes``.
  Gate/channel parts keep one extra live copy (input + donated output,
  the optimization_barrier liveness cut in fusion._plan_runner); a
  monolithic window remap keeps two (send + recv transient on top of
  the input — the pinned 2.0-shard number from the PR-3 pipelined
  exchange work), and a C-chunk pipelined remap keeps ``2/C`` (at most
  two chunk-sized transients in flight — the pinned 1.25-shard number
  at C=8).  The same numbers surface as the ``memory`` section of
  ``explain_circuit`` / reportCircuitPlan.

* **Enforcement** — ``admit_new`` gates createQureg /
  createDensityQureg / createBatchedQureg with a structured
  :class:`MemoryAdmissionError` naming predicted vs available bytes;
  ``govern_drain`` walks the degradation ladder when a drain's
  predicted peak exceeds budget: (1) raise the exchange chunk count to
  shrink remap temps, (2) split the program into smaller dispatch
  groups, (3) spill idle registers to host (raw permuted amps + perm +
  per-register RNG key bank behind a lazy handle that restores on next
  touch), and only then (4) refuse.  ``strict`` skips the ladder and
  raises before any device allocation.

* **OOM net** — :func:`oom_net` wraps every drain dispatch: a real (or
  FaultPlan-injected ``oom@W``) RESOURCE_EXHAUSTED evicts LRU-idle
  registers, clears the plan caches, backs off, and retries ONCE; a
  second failure propagates.

Every rung emits telemetry (``admission_rejects_total``,
``spills_total``, ``spill_bytes_total``, ``oom_retries_total``,
``governor_degradations_total{rung}``) and lands in the degradation
registry surfaced by getEnvironmentString.
"""

from __future__ import annotations

import os
import time
import weakref
from typing import List, Optional, Tuple

import numpy as np

from . import telemetry as _telemetry
from .validation import QuESTError

_POLICY_ENV = "QT_MEM_POLICY"
_BUDGET_ENV = "QT_HBM_BUDGET_BYTES"
_POLICIES = ("off", "degrade", "strict")

# --- live-copy multiplier model (docs/design.md §22) ---------------------
# A gate/channel part holds the donated output next to the input for the
# duration of one pass; a window remap additionally materializes its
# exchange transient: the WHOLE shard when monolithic (PR-3's pinned
# 2.0-shard peak), at most two in-flight chunks when pipelined over C
# chunks (the pinned 1.25-shard peak at C=8 -> extra = 2/C).
GATE_PART_EXTRA = 1.0


def remap_part_extra(chunks: int) -> float:
    """Extra live shard-copies of one ("remap", sigma) part at chunk
    count ``chunks`` — 2.0 monolithic, 1 + 2/C pipelined."""
    c = max(int(chunks), 1)
    return 2.0 if c <= 1 else 1.0 + 2.0 / c


class MemoryAdmissionError(QuESTError):
    """A register or drain was refused because its predicted per-device
    footprint exceeds the available HBM budget.  Carries the numbers so
    callers (and the pinned tests) can reason about the decision."""

    def __init__(self, func: str, predicted_bytes: int,
                 available_bytes: int, budget_bytes: int):
        self.predicted_bytes = int(predicted_bytes)
        self.available_bytes = int(available_bytes)
        self.budget_bytes = int(budget_bytes)
        super().__init__(
            f"{func}: predicted peak of {self.predicted_bytes} bytes per "
            f"device exceeds the {self.available_bytes} bytes available "
            f"under the {self.budget_bytes}-byte per-device HBM budget "
            f"(policy={policy()}; set {_BUDGET_ENV} / {_POLICY_ENV} to "
            f"adjust)")


class _InjectedOOM(RuntimeError):
    """Synthetic allocator failure raised by a FaultPlan ``oom@W`` event
    BEFORE the dispatch runs (so the donated input is never consumed);
    the message carries the XLA marker so _is_oom treats it like the
    real thing."""


def _is_oom(e: BaseException) -> bool:
    s = f"{type(e).__name__}: {e}"
    return ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s)


# ---------------------------------------------------------------------------
# Policy / budget resolution
# ---------------------------------------------------------------------------

# min-over-devices bytes_limit probe, cached per process (CPU -> None)
_DEVICE_LIMIT = [False, None]  # [probed, limit]


def policy() -> str:
    """``QT_MEM_POLICY``: off | degrade (default) | strict."""
    p = os.environ.get(_POLICY_ENV, "degrade").strip().lower() or "degrade"
    if p not in _POLICIES:
        from . import resilience

        resilience.record_degradation(
            "memory_governor_policy",
            f"unknown {_POLICY_ENV}={p!r}; using 'degrade'")
        return "degrade"
    return p


def _device_limit_bytes() -> Optional[int]:
    if not _DEVICE_LIMIT[0]:
        _DEVICE_LIMIT[0] = True
        limit = None
        try:
            import jax

            for d in jax.local_devices():
                try:
                    stats = d.memory_stats()
                # qlint: allow(broad-except): memory_stats() support and failure types are backend-dependent; a probe failure just means "no HBM cap known"
                except Exception:  # pragma: no cover - backend-dependent
                    stats = None
                cap = (stats or {}).get("bytes_limit")
                if cap is None:
                    limit = None
                    break
                limit = cap if limit is None else min(limit, cap)
        # qlint: allow(broad-except): device enumeration with no backend raises version-dependent types; the budget simply stays unknown
        except Exception:  # pragma: no cover - no backend at all
            limit = None
        _DEVICE_LIMIT[1] = int(limit) if limit else None
    return _DEVICE_LIMIT[1]


def budget_bytes() -> Optional[int]:
    """Per-device HBM budget: ``QT_HBM_BUDGET_BYTES`` override, else the
    min ``memory_stats()['bytes_limit']`` over local devices, else None
    (backend exposes no limit — the governor stays inert)."""
    raw = os.environ.get(_BUDGET_ENV)
    if raw is not None:
        try:
            v = int(raw)
            return v if v > 0 else None
        except ValueError:
            from . import resilience

            resilience.record_degradation(
                "memory_governor_budget",
                f"unparseable {_BUDGET_ENV}={raw!r}; ignoring")
            return _device_limit_bytes()
    return _device_limit_bytes()


def enabled() -> bool:
    return policy() != "off" and budget_bytes() is not None


# ---------------------------------------------------------------------------
# Register ledger
# ---------------------------------------------------------------------------


class _Entry:
    __slots__ = ("ref", "bytes", "tick", "spilled")

    def __init__(self, ref, nbytes: int, tick: int):
        self.ref = ref
        self.bytes = int(nbytes)
        self.tick = tick
        self.spilled = False


_LEDGER: dict = {}  # id(qureg) -> _Entry (weakly referenced)
_TICK = [0]
# max modeled (resident + drain transient) bytes seen this process — the
# watermark the CPU dryrun publishes in place of device memory_stats
_MODELED_PEAK: List[Optional[int]] = [None]


def register_bytes_per_device(qureg) -> int:
    """Modeled steady-state bytes ONE device holds for ``qureg``:
    ``B x 2 x 2^n x itemsize`` split over the amplitude shards (a
    register too small to shard is replicated — full bytes per device,
    mirroring Qureg.sharding)."""
    b = max(int(getattr(qureg, "batch_size", 0) or 0), 1)
    total = b * 2 * qureg.num_amps_total * np.dtype(qureg.dtype).itemsize
    env = qureg.env
    if env.mesh is not None and qureg.num_amps_total >= env.num_devices:
        return total // env.num_devices
    return total


def refresh_budget() -> None:
    """Re-derive the per-device budget and re-price the ledger after the
    live mesh changes shape (serve failover/heal, elastic failover): the
    HBM probe cache is dropped — the next :func:`budget_bytes` re-probes
    whatever devices survive — and every resident entry's per-device
    bytes are recomputed against its register's CURRENT environment
    (fewer devices -> more bytes per device, and vice versa on heal)."""
    _DEVICE_LIMIT[0] = False
    _DEVICE_LIMIT[1] = None
    for key in list(_LEDGER):
        e = _LEDGER.get(key)
        q = e.ref() if e is not None else None
        if q is None:
            _LEDGER.pop(key, None)
            continue
        if not e.spilled:
            e.bytes = register_bytes_per_device(q)
    _telemetry.inc("governor_budget_rederivations_total")


def _next_tick() -> int:
    _TICK[0] += 1
    return _TICK[0]


def track(qureg) -> None:
    """Enter ``qureg`` into the ledger (idempotent; always on — the dict
    insert is negligible and keeps 'resident bytes' truthful even when
    the budget is enabled mid-process, as tests do)."""
    key = id(qureg)

    def _gone(_ref, _key=key):
        _LEDGER.pop(_key, None)

    _LEDGER[key] = _Entry(weakref.ref(qureg, _gone),
                          register_bytes_per_device(qureg), _next_tick())


def release(qureg) -> None:
    """Drop ``qureg`` from the ledger (destroyQureg)."""
    _LEDGER.pop(id(qureg), None)


def touch(qureg) -> None:
    """Bump the LRU tick (any drain or restore of the register)."""
    e = _LEDGER.get(id(qureg))
    if e is not None:
        e.tick = _next_tick()


def resident_bytes(exclude=None) -> int:
    """Modeled bytes currently resident per device across tracked
    registers (spilled and destroyed registers do not count)."""
    ex = id(exclude) if exclude is not None else None
    total = 0
    for key in list(_LEDGER):
        e = _LEDGER.get(key)
        if e is None:
            continue
        q = e.ref()
        if q is None:
            _LEDGER.pop(key, None)
            continue
        if key == ex or e.spilled or q._amps is None:
            continue
        total += e.bytes
    return total


# ---------------------------------------------------------------------------
# Admission (register creation)
# ---------------------------------------------------------------------------


def admit_new(qureg, func: str) -> None:
    """Gate a new register BEFORE its device allocation: with a budget
    enabled, refuse (MemoryAdmissionError naming predicted vs available
    bytes) when the modeled footprint does not fit next to the resident
    set — the governed analogue of QuEST's validateMemoryAllocationSize,
    turned from an abort into a structured error."""
    if not enabled():
        track(qureg)
        return
    need = register_bytes_per_device(qureg)
    b = budget_bytes()
    avail = b - resident_bytes()
    if need > avail:
        _telemetry.inc("admission_rejects_total", func=func)
        raise MemoryAdmissionError(func, need, avail, b)
    track(qureg)


# ---------------------------------------------------------------------------
# Spill-to-host eviction
# ---------------------------------------------------------------------------


class SpillHandle:
    """Host-side snapshot of an evicted register: RAW (possibly
    permuted) amplitudes, the live logical->physical permutation, the
    dtype, and — for a BatchedQureg — the per-element measurement key
    bank (the only per-register RNG state; scalar registers draw from
    the process-global stream).  Restored lazily on the next touch
    (Qureg.amps / _amps_raw)."""

    __slots__ = ("amps", "perm", "dtype", "key_state", "nbytes")

    def __init__(self, amps: np.ndarray, perm, dtype, key_state):
        self.amps = amps
        self.perm = None if perm is None else tuple(perm)
        self.dtype = np.dtype(dtype)
        self.key_state = key_state
        self.nbytes = int(amps.nbytes)


class _SparseHandle:
    """Lazy sparse-state handle (§28): ``initSparseState`` admits at the
    cost of its indices + amplitude values and defers the dense
    ``(2, 2^n)`` materialization to the first touch, where
    :func:`restore_register` runs it under the ordinary admission
    machinery (``spill_until`` makes room first).  Duck-types
    :class:`SpillHandle` — restore reads ``.amps`` / ``.perm`` /
    ``.dtype`` / ``.key_state`` and never learns the state was sparse."""

    __slots__ = ("indices", "res", "ims", "num_amps", "perm", "dtype",
                 "key_state", "nbytes")

    def __init__(self, num_amps: int, indices, res, ims, dtype):
        self.num_amps = int(num_amps)
        self.indices = np.asarray(indices, dtype=np.int64)
        self.res = np.asarray(res, dtype=np.dtype(dtype))
        self.ims = np.asarray(ims, dtype=np.dtype(dtype))
        self.perm = None
        self.dtype = np.dtype(dtype)
        self.key_state = None
        self.nbytes = int(self.indices.nbytes + self.res.nbytes
                          + self.ims.nbytes)

    @property
    def amps(self) -> np.ndarray:
        out = np.zeros((2, self.num_amps), dtype=self.dtype)
        out[0, self.indices] = self.res
        out[1, self.indices] = self.ims
        return out


def admit_sparse_state(qureg, indices, res, ims,
                       func: str = "initSparseState") -> None:
    """Install a lazy sparse state: the register's device buffer is
    dropped, the handle is admitted at SPARSE cost (indices + amplitude
    values, NOT the dense 2^n footprint), and densification happens on
    the first touch through restore_register — under admission control,
    so a budget that cannot hold the dense state TODAY still accepts the
    sparse description and spills neighbours when the drain arrives."""
    h = _SparseHandle(1 << qureg.num_qubits_in_state_vec,
                      indices, res, ims, qureg.dtype)
    if enabled():
        b = budget_bytes()
        avail = b - resident_bytes(exclude=qureg)
        if h.nbytes > avail:
            _telemetry.inc("admission_rejects_total", func=func)
            raise MemoryAdmissionError(func, h.nbytes, avail, b)
    qureg._amps = None
    qureg._perm = None
    qureg._spill = h
    e = _LEDGER.get(id(qureg))
    if e is None:
        track(qureg)
        e = _LEDGER[id(qureg)]
    e.spilled = True


def spill_register(qureg) -> int:
    """Evict ``qureg``'s amplitudes to host memory behind a lazy
    :class:`SpillHandle`; returns the modeled per-device bytes freed
    (0 when there was nothing resident).  Pending fused gates stay
    buffered — the restore happens before any drain reads the amps."""
    raw = qureg._amps
    if raw is None or getattr(qureg, "_spill", None) is not None:
        return 0
    host = np.asarray(raw)
    key_state = qureg.key_state() if hasattr(qureg, "key_state") else None
    qureg._spill = SpillHandle(host, qureg._perm, qureg.dtype, key_state)
    qureg._amps = None
    qureg._perm = None
    e = _LEDGER.get(id(qureg))
    if e is None:
        track(qureg)
        e = _LEDGER[id(qureg)]
    e.spilled = True
    _telemetry.inc("spills_total")
    _telemetry.inc("spill_bytes_total", host.nbytes)
    return e.bytes


def restore_register(qureg) -> bool:
    """Bring a spilled register back on device (bit-identical: raw
    permuted amps + perm + key bank); returns False when the register
    was never spilled (so Qureg.amps can raise its destroyed-register
    error instead)."""
    h = getattr(qureg, "_spill", None)
    if h is None:
        return False
    import jax
    import jax.numpy as jnp

    qureg._spill = None
    e = _LEDGER.get(id(qureg))
    if e is not None:
        e.spilled = False
    if enabled():
        # make room for the returning register before device_put
        need = register_bytes_per_device(qureg)
        b = budget_bytes()
        if resident_bytes(exclude=qureg) + need > b:
            spill_until(need, exclude=qureg)
    qureg.dtype = h.dtype
    amps = jax.device_put(jnp.asarray(h.amps, h.dtype), qureg.sharding())
    qureg._set_amps_permuted(amps, h.perm)
    if h.key_state is not None:
        qureg.set_key_state(h.key_state)
    touch(qureg)
    _telemetry.inc("spill_restores_total")
    return True


def ensure_resident(qureg) -> None:
    """Restore ``qureg`` if a prior ladder pass spilled it (the fusion
    drain reads qureg._amps directly, bypassing the property)."""
    if getattr(qureg, "_spill", None) is not None:
        restore_register(qureg)


def _spill_candidates(exclude=None) -> list:
    ex = id(exclude) if exclude is not None else None
    out = []
    for key, e in list(_LEDGER.items()):
        q = e.ref()
        if q is None or key == ex or e.spilled or q._amps is None:
            continue
        out.append((e.tick, e, q))
    out.sort(key=lambda t: t[0])  # least-recently-used first
    return out


def spill_until(need: int, exclude=None) -> int:
    """Spill idle registers in LRU order until ``need`` bytes fit under
    the budget next to what remains resident; returns bytes freed."""
    b = budget_bytes()
    freed = 0
    for _tick, _e, q in _spill_candidates(exclude):
        if b is None or resident_bytes(exclude=exclude) + need <= b:
            break
        freed += spill_register(q)
    return freed


def spill_all_idle(exclude=None) -> int:
    """Evict every idle register (the OOM net's desperation move)."""
    freed = 0
    for _tick, _e, q in _spill_candidates(exclude):
        freed += spill_register(q)
    return freed


# ---------------------------------------------------------------------------
# Drain prediction + degradation ladder
# ---------------------------------------------------------------------------


def _arrays_bytes(arrays) -> int:
    return int(sum(int(getattr(a, "nbytes", 0) or 0) for a in arrays))


def _resolved_chunks(nloc: int, itemsize: int, nsh: int) -> int:
    """Full-shard chunk count the remap parts will resolve under the
    LIVE chunk policy (env override / governor override / heuristic)."""
    if not nsh:
        return 1
    from .parallel import dist as PAR

    return int(PAR.remap_chunk_plan(nloc, itemsize)[1])


def _program_peak(program, state: int, arrays_b: int, chunks: int) -> int:
    """Predicted per-device peak of dispatching ``program`` as ONE
    group: state x (1 + max part extra) + pass-array bytes."""
    extra = 0.0
    for part in program:
        pe = (remap_part_extra(chunks) if part[0] == "remap"
              else GATE_PART_EXTRA)
        extra = max(extra, pe)
    return int(state * (1.0 + extra)) + int(arrays_b)


def predict_drain(qureg, program, arrays, *, nloc: int, nsh: int,
                  chunks: Optional[int] = None) -> dict:
    """Analytic per-device footprint of draining ``program`` on
    ``qureg`` — the quantity govern_drain enforces and explain_circuit's
    ``memory`` section reports."""
    itemsize = np.dtype(qureg.dtype).itemsize
    state = register_bytes_per_device(qureg)
    arrays_b = _arrays_bytes(arrays)
    c = chunks if chunks is not None else _resolved_chunks(
        nloc, itemsize, nsh)
    peak = (_program_peak(program, state, arrays_b, c) if program
            else state)
    other = resident_bytes(exclude=qureg)
    b = budget_bytes()
    # per-interconnect-tier exchange bytes of the drain's remap parts —
    # the hierarchical (QT_TOPOLOGY) refinement of the exchange volume,
    # weighted by the relative link cost so the drain-peak report also
    # says how much of its traffic rides the slow DCN tier
    tier_b = {"ici": 0, "dcn": 0}
    if nsh:
        from .parallel import dist as PAR
        from .parallel import topology as _topo

        topology = _topo.resolve(1 << nsh)
        for part in program:
            if part[0] != "remap":
                continue
            for t, (_cnt, nb) in PAR.remap_exchange_tiers(
                    part[1], nloc, nsh, itemsize, topology).items():
                tier_b[t] += nb
        weights = _topo.tier_weights()
    else:
        weights = {"ici": 1.0, "dcn": 1.0}
    return {
        "policy": policy(),
        "budget_bytes": b,
        "state_bytes_per_device": int(state),
        "pass_array_bytes": int(arrays_b),
        "live_multiplier": round(
            (peak - arrays_b) / state, 4) if state else 1.0,
        "exchange_chunks": int(c),
        "predicted_peak_bytes": int(peak),
        "other_resident_bytes": int(other),
        "predicted_total_bytes": int(other + peak),
        "headroom_bytes": (None if b is None
                           else int(b - other - peak)),
        "fits": (None if b is None else bool(other + peak <= b)),
        "exchange_tier_bytes": {t: int(v) for t, v in tier_b.items()},
        "weighted_exchange_cost": float(sum(
            weights[t] * v for t, v in tier_b.items())),
    }


def _split_program(program, arrays, state: int, other: int, b: int,
                   chunks: int):
    """Rung 2: greedily pack program parts into contiguous dispatch
    groups so each group's peak (state x (1+max extra) + its own pass
    arrays) fits the remaining budget.  Part boundaries already carry an
    optimization_barrier in the single-program executor, so the grouped
    execution is bit-identical — only the dispatch count changes.
    Returns a tuple of part-groups, or None when grouping cannot help
    (single part, or a lone part already over budget)."""
    sizes = []
    ai = 0
    for part in program:
        na = part[2] if part[0] == "plan" else 0
        sizes.append(_arrays_bytes(arrays[ai:ai + na]))
        ai += na
    groups: List[tuple] = []
    cur: List[tuple] = []
    for part, _sb in zip(program, sizes):
        trial = cur + [part]
        start = sum(len(g) for g in groups)
        trial_b = sum(sizes[start:start + len(trial)])
        if cur and other + _program_peak(
                trial, state, trial_b, chunks) > b:
            groups.append(tuple(cur))
            cur = [part]
        else:
            cur = trial
    if cur:
        groups.append(tuple(cur))
    if len(groups) <= 1:
        return None
    # feasible only if every group now fits
    start = 0
    for g in groups:
        gb = sum(sizes[start:start + len(g)])
        start += len(g)
        if other + _program_peak(g, state, gb, chunks) > b:
            return None
    return tuple(groups)


def govern_drain(qureg, program, arrays, *, nloc: int, nsh: int):
    """Enforce the budget on one planned drain.  Returns None when the
    governor is inert or the drain fits untouched; otherwise a dict
    ``{"groups": tuple-of-part-groups or None, "chunks": C or None}``
    after walking the degradation ladder (chunk bump -> program split ->
    spill idle registers -> refuse).  ``strict`` skips the ladder and
    raises :class:`MemoryAdmissionError` before any device allocation;
    the fusion drain's failure path restores the gate buffer, so state
    and QASM log stay consistent."""
    if not enabled() or not program:
        touch(qureg)
        return None
    touch(qureg)
    from . import resilience as _res
    from .parallel import dist as PAR

    b = budget_bytes()
    itemsize = np.dtype(qureg.dtype).itemsize
    state = register_bytes_per_device(qureg)
    arrays_b = _arrays_bytes(arrays)
    other = resident_bytes(exclude=qureg)
    c0 = _resolved_chunks(nloc, itemsize, nsh)
    need = _program_peak(program, state, arrays_b, c0)
    if other + need <= b:
        _record_usage(other + need)
        return None
    if policy() == "strict":
        _telemetry.inc("admission_rejects_total", func="drain")
        raise MemoryAdmissionError("gateFusion drain", need, b - other, b)

    applied = []
    # rung 1: pipeline the window remaps harder (shrinks the exchange
    # transient from a whole shard to 2/C of one).  The explicit
    # QT_EXCHANGE_CHUNKS override is the user's word — never fought.
    c = c0
    if (nsh and any(p[0] == "remap" for p in program)
            and os.environ.get(PAR._EXCHANGE_ENV) is None):
        cap = min(PAR.MAX_EXCHANGE_CHUNKS, 1 << max(nloc - 1, 0))
        pick = None
        t = max(c0, 1)
        while t < cap:
            t *= 2
            if other + _program_peak(program, state, arrays_b, t) <= b:
                pick = t
                break
        if pick is None and cap > c0:
            pick = cap  # max shrink, ladder continues
        if pick is not None and pick != c0:
            c = pick
            PAR._GOVERNOR_CHUNKS[0] = int(c)
            applied.append(("chunks",
                            f"exchange chunks {c0} -> {c} to shrink "
                            "remap transients"))
            need = _program_peak(program, state, arrays_b, c)

    # rung 2: split the oversized window into smaller dispatch groups
    groups = None
    if other + need > b:
        groups = _split_program(program, arrays, state, other, b, c)
        if groups is not None:
            applied.append(("split",
                            f"drain split into {len(groups)} dispatch "
                            "groups"))
            need = _max_group_peak(groups, arrays, state, c)

    # rung 3: spill idle registers (LRU) to free co-resident bytes
    if other + need > b:
        freed = spill_until(need, exclude=qureg)
        if freed:
            applied.append(("spill",
                            f"spilled {freed} resident bytes of idle "
                            "registers to host"))
        other = resident_bytes(exclude=qureg)

    if other + need > b:
        _telemetry.inc("admission_rejects_total", func="drain")
        _rollback_chunks()
        raise MemoryAdmissionError("gateFusion drain", need, b - other, b)

    for rung, why in applied:
        _telemetry.inc("governor_degradations_total", rung=rung)
        _res.record_degradation("memory_governor_" + rung, why)
    _record_usage(other + need)
    return {"groups": groups, "chunks": c if c != c0 else None}


def _max_group_peak(groups, arrays, state: int, chunks: int) -> int:
    """Exact max per-group peak: walks the pass-array offsets group by
    group (the same accounting fusion's dispatch loop uses)."""
    ai = 0
    worst = 0
    for g in groups:
        na = sum(p[2] if p[0] == "plan" else 0 for p in g)
        gb = _arrays_bytes(arrays[ai:ai + na])
        ai += na
        worst = max(worst, _program_peak(g, state, gb, chunks))
    return worst


def _rollback_chunks() -> None:
    from .parallel import dist as PAR

    PAR._GOVERNOR_CHUNKS[0] = None


def end_drain() -> None:
    """Clear the per-drain chunk escalation (fusion._run's finally)."""
    _rollback_chunks()


def _record_usage(total: int) -> None:
    prev = _MODELED_PEAK[0]
    _MODELED_PEAK[0] = max(int(total), prev or 0)


def modeled_watermark_bytes() -> Optional[int]:
    """Max modeled (resident + transient) per-device bytes any governed
    drain reached — published as ``hbm_watermark_bytes{device="model"}``
    by utils.profiling.memory_watermark when the backend exposes no
    memory_stats, so the CPU dryrun's watermark agrees with the
    predictor instead of reporting host RSS."""
    if not enabled():
        return None
    return _MODELED_PEAK[0]


# ---------------------------------------------------------------------------
# OOM net (last resort)
# ---------------------------------------------------------------------------


def oom_net(fn, qureg=None):
    """Run ``fn()`` (one drain dispatch) under the RESOURCE_EXHAUSTED
    net: on an allocator failure — real, or injected by a FaultPlan
    ``oom@W`` event — evict LRU-idle registers, clear the plan caches,
    back off, and retry ONCE.  A second failure propagates.  Injected
    faults raise BEFORE the dispatch consumes its donated input, so the
    deterministic CI path is always state-safe; the real-OOM retry is a
    documented best effort."""

    from . import resilience as _res

    plan = _res._ACTIVE_FAULTS[0]
    if plan is not None:
        # a drain outside run_resumable never reaches arm_exchange_window;
        # its oom@W events count as window 0
        plan.arm_oom(0)

    def attempt():
        if plan is not None and plan.take_oom_fault():
            raise _InjectedOOM(
                "RESOURCE_EXHAUSTED: injected allocation failure "
                "(FaultPlan oom)")
        return fn()

    try:
        return attempt()
    # qlint: allow(broad-except): the oom_net — XLA surfaces RESOURCE_EXHAUSTED under backend-specific exception classes, so the net catches everything, re-raises non-OOM unchanged, and retries once after eviction
    except Exception as e:
        if not _is_oom(e):
            raise
        _recover_from_oom(qureg, e)
        return attempt()


def _recover_from_oom(qureg, err) -> None:
    from . import fusion as _fusion
    from . import resilience as _res

    _telemetry.inc("oom_retries_total")
    _telemetry.inc("governor_degradations_total", rung="oom_retry")
    _res.record_degradation(
        "memory_governor_oom_retry",
        f"RESOURCE_EXHAUSTED at dispatch ({err!s:.120}); evicted idle "
        "registers and cleared plan caches for one retry")
    spill_all_idle(exclude=qureg)
    _fusion._plan_cache.clear()
    _fusion._plan_runner.cache_clear()
    try:
        import jax

        jax.clear_caches()
    # qlint: allow(broad-except): clear_caches is a version-dependent API; OOM recovery must proceed to the retry even when it is absent or fails
    except Exception:  # pragma: no cover - version-dependent API
        pass
    time.sleep(float(os.environ.get("QT_RETRY_BASE_SECONDS", "0.05")))


# ---------------------------------------------------------------------------
# Introspection / report surfaces
# ---------------------------------------------------------------------------


def explain_memory(qureg, items) -> dict:
    """The ``memory`` section of explain_circuit: plan ``items`` quietly
    (no telemetry, no plan-cache insertion — the dry-run contract) and
    run the predictor over the exact program the drain would dispatch."""
    from . import fusion as F

    program, arrays, _fp, nloc, nsh = F.plan_items_quiet(qureg, items)
    return predict_drain(qureg, program, arrays, nloc=nloc, nsh=nsh)


def summary_line() -> Optional[str]:
    """One-line governor status for reportPerf (None when inert and
    nothing ever fired)."""
    rejects = _telemetry.counter_total("admission_rejects_total")
    spills = _telemetry.counter_total("spills_total")
    ooms = _telemetry.counter_total("oom_retries_total")
    if not enabled() and not (rejects or spills or ooms):
        return None
    b = budget_bytes()
    parts = [f"memory governor: policy={policy()}",
             f"budget={b if b is not None else '-'}",
             f"resident={resident_bytes()}"]
    peak = _MODELED_PEAK[0]
    if peak is not None:
        parts.append(f"modeled_peak={peak}")
    parts.append(f"rejects={int(rejects)} spills={int(spills)} "
                 f"oom_retries={int(ooms)}")
    return " ".join(parts)


def reset() -> None:
    """Forget all governor state (tests): ledger, modeled peak, device
    probe, any live chunk escalation."""
    _LEDGER.clear()
    _TICK[0] = 0
    _MODELED_PEAK[0] = None
    _DEVICE_LIMIT[0] = False
    _DEVICE_LIMIT[1] = None
    try:
        _rollback_chunks()
    # qlint: allow(broad-except): reset() must succeed even before parallel/dist is importable (circular-import window during package init)
    except Exception:  # pragma: no cover - dist not importable yet
        pass
