"""Precision configuration for quest_tpu.

TPU-native analogue of the reference's compile-time precision switch
(``QuEST/include/QuEST_precision.h``): the reference selects ``qreal`` as
float/double/long-double via the ``QuEST_PREC`` CMake cache variable
(QuEST_precision.h:28-68).  Here precision is a *runtime* (trace-time)
setting: new registers are created with the currently configured dtype.

TPU hardware natively computes f32 (and bf16); f64 is software-emulated and
~10x slower, so the TPU-first default is single precision.  Double precision
is fully supported (enable ``jax.config.update("jax_enable_x64", True)``)
and is what the test-suite oracle comparisons use on CPU.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

# Reference epsilon-per-precision (QuEST_precision.h:28-68): 1e-5 single,
# 1e-13 double, 1e-14 quad.  Used by unitarity / CPTP / probability
# validation.
_REAL_EPS = {1: 1e-5, 2: 1e-13, 4: 1e-14}

# Reference cap on qubits in applyMultiVarPhaseFunc-style register lists
# (QuEST_precision.h:72).
MAX_NUM_REGS_APPLY_ARBITRARY_PHASE = 100


@dataclasses.dataclass
class _PrecisionState:
    quest_prec: int = 1  # 1 = single (f32/c64), 2 = double (f64/c128)


_state = _PrecisionState()


def set_precision(quest_prec: int) -> None:
    """Set the working precision: 1 = single (f32), 2 = double (f64),
    4 = quad (QuEST_PREC=4, QuEST_precision.h:55-68).

    Quad-precision SCOPE (the recorded decision VERDICT r3 item 7 asked
    for): amplitude STORAGE stays f64 — no accelerator exposes an f128
    type, and the reference itself forbids quad on its GPU backend
    ("Quad precision unsupported on GPU", QuEST/CMakeLists.txt:69-73),
    so the TPU backend inherits exactly that restriction for storage.
    What prec 4 DOES change: REAL_EPS tightens to the reference's 1e-14
    (validation of user matrices stays at the f64 tolerance — see
    validation_eps), the message cap drops to 2^27 amps, and EVERY
    scalar reduction where extended precision is observable accumulates
    in double-double via error-free-transform compensation
    (ops/calculations.py quad paths + the paulis expectation scans):
    calcTotalProb, inner products, purity, fidelity, Hilbert-Schmidt
    distance, expec-diagonal, prob-of-outcome, and the Pauli-sum
    expectation scans (sharded included) — the reductions the reference
    runs in long double under QuEST_PREC=4
    (QuEST_cpu.c:861-1071,3363-3645).
    """
    if quest_prec not in (1, 2, 4):
        raise ValueError(
            "quest_prec must be 1 (single), 2 (double) or 4 (quad)")
    if quest_prec in (2, 4):
        jax.config.update("jax_enable_x64", True)
    _state.quest_prec = quest_prec


def get_precision() -> int:
    return _state.quest_prec


def real_dtype():
    return jnp.float64 if _state.quest_prec in (2, 4) else jnp.float32


def complex_dtype():
    return jnp.complex128 if _state.quest_prec in (2, 4) else jnp.complex64


def real_eps() -> float:
    """Reported epsilon, matching QuEST_precision.h REAL_EPS."""
    return _REAL_EPS[_state.quest_prec]


def validation_eps() -> float:
    """Tolerance for unitarity / CPTP / normalisation checks of
    user-supplied matrices and scalars.  Under prec 4 this stays at the
    f64 value (1e-13): the check arithmetic itself runs in f64 (the
    reference's quad mode validates in long double, where 1e-14 is
    comfortable — here a valid matrix can sit at the f64 rounding floor
    and 1e-14 would falsely reject it; ADVICE r4).  The tightened 1e-14
    is reserved for the compensated-reduction outputs.  This deliberate
    divergence is documented user-facing in docs/design.md §15 and the
    README precision section."""
    return _REAL_EPS[min(_state.quest_prec, 2)]


# Reference cap on amps per MPI message / full-state host gather
# (MPI_MAX_AMPS_IN_MSG, QuEST_precision.h:32,46,61: ~2 GB per message —
# 2^29 amps single, 2^28 double).  quest_tpu applies it where a whole
# state would be gathered to one host buffer (compareStates, CSV
# loaders, reportStateToScreen — the reference guards its toQVector the
# same way, utilities.cpp:1073-1074).
_MAX_AMPS_IN_MSG = {1: 1 << 29, 2: 1 << 28, 4: 1 << 27}


def max_amps_in_msg() -> int:
    return _MAX_AMPS_IN_MSG[_state.quest_prec]
