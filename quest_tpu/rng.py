"""Measurement RNG.

The reference uses a Mersenne Twister (mt19937ar.c) seeded from time+pid and
broadcast so every rank draws identical outcomes (QuEST_common.c:195-227,
QuEST_cpu_distributed.c:1384-1395).  Here we keep the same generator family
(numpy's MT19937) for the imperative ``measure`` API — host-side sampling is
inherently a device->host sync, matching the reference's semantics — and
additionally expose key-based ``jax.random`` sampling for fully-jitted
measurement (quest_tpu.ops.measurement), which the reference cannot do.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence

import numpy as np


class _MeasurementRNG:
    def __init__(self):
        self.seed_default()

    def seed(self, seeds: Sequence[int]) -> None:
        self._keys = [int(s) & 0xFFFFFFFF for s in seeds]
        self._rng = np.random.RandomState(np.random.MT19937(np.array(self._keys, dtype=np.uint32)))

    def seed_default(self) -> None:
        """time + pid default-key seeding (QuEST_common.c:195-217)."""
        self.seed([int(time.time()), os.getpid()])

    def uniform(self) -> float:
        return float(self._rng.random_sample())


GLOBAL_RNG = _MeasurementRNG()
