"""Measurement RNG.

The reference uses a Mersenne Twister (mt19937ar.c) seeded from time+pid and
broadcast so every rank draws identical outcomes (QuEST_common.c:195-227,
QuEST_cpu_distributed.c:1384-1395).  Here we keep the same generator family
(numpy's MT19937) for the imperative ``measure`` API — host-side sampling is
inherently a device->host sync, matching the reference's semantics — and
additionally expose key-based ``jax.random`` sampling for fully-jitted
measurement (quest_tpu.ops.measurement), which the reference cannot do.

Reproducibility contract: the time+pid DEFAULT seed is the one
nondeterminism source the package cannot avoid (the reference's semantics
require it).  It is therefore always RECORDED — one ``quest_tpu.rng``
JSON line on stderr at default-seed time, the chosen keys surfaced as
``DefaultSeed=`` in ``getEnvironmentString`` (env.py), and
:attr:`_MeasurementRNG.default_seeded` marking streams that were never
explicitly seeded — so any run, however started, is replayable with
``seedQuEST(env, <logged keys>)``.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional, Sequence

import numpy as np


class _MeasurementRNG:
    def __init__(self):
        self.seed_default()

    def seed(self, seeds: Sequence[int]) -> None:
        self._keys = [int(s) & 0xFFFFFFFF for s in seeds]
        self._rng = np.random.RandomState(np.random.MT19937(np.array(self._keys, dtype=np.uint32)))
        self.default_seeded = False

    def seed_default(self) -> None:
        """time + pid default-key seeding (QuEST_common.c:195-217),
        with the chosen keys logged so the run stays replayable."""
        # qlint: allow(nondeterminism): QuEST's documented default-seed source (time+pid); the keys are logged below and surfaced as DefaultSeed= in getEnvironmentString so any run replays via seedQuEST
        self.seed([int(time.time()), os.getpid()])
        self.default_seeded = True
        print(json.dumps({"event": "quest_tpu.rng.default_seed",
                          "seeds": self._keys}),
              file=sys.stderr, flush=True)

    def uniform(self) -> float:
        return float(self._rng.random_sample())

    # -- state round-trip (resumable execution, resilience.py) --

    def get_state(self) -> dict:
        """JSON-serializable MT19937 state snapshot: restoring it with
        :meth:`set_state` continues the measurement-outcome stream exactly
        where it left off, so a resumed run draws the same outcomes an
        uninterrupted run would."""
        name, key, pos, has_gauss, cached = self._rng.get_state()
        return {
            "seeds": [int(k) for k in self._keys],
            "algo": name,
            "key": [int(x) for x in key],
            "pos": int(pos),
            "has_gauss": int(has_gauss),
            "cached_gaussian": float(cached),
        }

    def set_state(self, state: dict) -> None:
        """Restore a snapshot from :meth:`get_state` (bit-exact stream
        continuation)."""
        self._keys = [int(k) & 0xFFFFFFFF for k in state["seeds"]]
        self._rng = np.random.RandomState(
            np.random.MT19937(np.array(self._keys, dtype=np.uint32)))
        self._rng.set_state((
            state.get("algo", "MT19937"),
            np.array(state["key"], dtype=np.uint32),
            int(state["pos"]),
            int(state["has_gauss"]),
            float(state["cached_gaussian"]),
        ))
        self.default_seeded = False


GLOBAL_RNG = _MeasurementRNG()
