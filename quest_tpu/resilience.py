"""Fault-tolerant execution: resumable circuit runs, fault injection, and
a numerical-health watchdog.

The reference QuEST has no persistence story beyond a debug CSV dump
(reportState, QuEST_common.c:229-245) — a crashed multi-hour run loses
everything.  On preemptible TPU pods (ROADMAP.md north star) preemption is
the COMMON case, and distributed simulators at this scale treat long-run
survivability and numerical drift as first-class engineering problems
(mpiQulacs, arXiv:2203.16044 §V; qHiPSTER, arXiv:1601.07195 §IV).  This
module is that layer for quest_tpu:

* **Resumable execution** — :func:`run_resumable` drives a gate stream in
  fusion windows of ``every`` gates, checkpointing at window boundaries
  (never mid-window) with a generation protocol: a new generation is
  written beside the last-good one and only *committed* (an atomic
  ``LATEST`` pointer rename) after the asynchronous orbax write finishes,
  so a crash mid-save always leaves a loadable checkpoint.  The metadata
  extends ``saveQureg``'s with the circuit cursor (gate index), the live
  logical->physical permutation (``Qureg._perm`` — saved RAW, because
  rematerializing canonical order would change the downstream fold order
  and break bit-exact resume), and the measurement-RNG state (host MT19937
  + device key/shot counter), so a resumed run is bit-identical to an
  uninterrupted one.

* **Fault injection** — a deterministic :class:`FaultPlan`
  (``QT_FAULT_PLAN`` env var or programmatic) injects preemption-style
  kills between windows, kills mid-save, post-commit checkpoint
  corruption, transient IO errors (exercising :func:`retry_io`'s bounded
  exponential backoff), amplitude NaN/Inf corruption in one shard, and
  norm drift.

* **Numerical-health watchdog** — :func:`check_qureg_health` is one
  jitted on-device scan (sum of |amps|^2 — a psum across shards under
  GSPMD — plus an isfinite reduction) costing a single scalar readback;
  :func:`run_resumable` runs it every window and before every checkpoint,
  with policies ``raise`` (structured :class:`NumericalHealthError` naming
  the offending window), ``renormalize`` (norm-drift only), and
  ``rollback`` (restore the last-good checkpoint, then raise with the
  rollback context so the caller can re-enter ``run_resumable``).

* **Graceful degradation** — a process-wide registry
  (:func:`record_degradation`) that subsystems report irreversible
  downgrades into (e.g. ops/paulis.py falling back from the fused Pallas
  direct-rotation kernel to the XLA gather path when lowering fails);
  ``getEnvironmentString`` (env.py) appends the report.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import time
import warnings
from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import telemetry as _telemetry
from .validation import QuESTError

# structured run-context logging: every checkpoint/restore/watchdog event
# in run_resumable emits ONE JSON line through this stdlib logger (no
# bare prints; operators attach handlers / pytest captures via caplog)
_RUN_LOG = logging.getLogger("quest_tpu.resilience")


def _log_event(run_id: str, event: str, **fields) -> None:
    payload = {"event": event, "run": run_id}
    payload.update(fields)
    _RUN_LOG.info(json.dumps(payload, sort_keys=True))

# ---------------------------------------------------------------------------
# Degradation registry (graceful-downgrade observability)
# ---------------------------------------------------------------------------

# name -> reason; written once per process by subsystems that fell back to
# a slower-but-working path (env.get_environment_string reports it)
DEGRADATIONS: dict = {}


def record_degradation(name: str, reason: str) -> None:
    """Record (and warn about, once) an irreversible in-process downgrade
    — e.g. a Pallas kernel that failed to lower and fell back to XLA."""
    if name in DEGRADATIONS:
        return
    DEGRADATIONS[name] = reason
    _telemetry.inc("degradations_total", name=name)
    _telemetry.flight_event("degradation", name=name, reason=reason)
    warnings.warn(f"quest_tpu degraded: {name}: {reason}", stacklevel=2)


def degradation_report() -> dict:
    """Snapshot of every recorded downgrade (name -> reason)."""
    return dict(DEGRADATIONS)


# ---------------------------------------------------------------------------
# Errors
# ---------------------------------------------------------------------------


class SimulatedPreemption(RuntimeError):
    """Raised by an injected ``kill``/``killsave`` fault — stands in for
    the SIGKILL a preemptible pod receives; deliberately NOT a QuESTError
    so resilience tests can't confuse it with a validation failure."""


class NumericalHealthError(QuESTError):
    """The watchdog found a non-finite amplitude or norm drift beyond
    tolerance.  Carries the offending window so logs name the gate range,
    and the rollback cursor when the ``rollback`` policy restored state."""

    def __init__(self, msg: str, *, window: Optional[Tuple[int, int]] = None,
                 norm: Optional[float] = None, finite: bool = True,
                 rolled_back_to: Optional[int] = None,
                 element: Optional[int] = None):
        super().__init__(msg)
        self.window = window
        self.norm = norm
        self.finite = finite
        self.rolled_back_to = rolled_back_to
        # worst batch-element index on a BatchedQureg bank (None for a
        # scalar register) — the serving layer's quarantine bisection
        # uses it to attribute a poisoned bank to ONE member job
        self.element = element


# ---------------------------------------------------------------------------
# Bounded exponential-backoff retry for checkpoint IO
# ---------------------------------------------------------------------------

# transient-IO retry policy: attempts and base delay are env-tunable so
# tests (and impatient operators) can shrink the backoff
_RETRY_ATTEMPTS_ENV = "QT_RETRY_ATTEMPTS"
_RETRY_BASE_ENV = "QT_RETRY_BASE_SECONDS"

# the FaultPlan currently driving a run_resumable (or a test) — retry_io
# consults it for injected transient errors
_ACTIVE_FAULTS: List[Optional["FaultPlan"]] = [None]

# env seed for the backoff-jitter stream (and the chaos harness): when
# set, every retrier on this process jitters deterministically
_CHAOS_SEED_ENV = "QT_CHAOS_SEED"

# dedicated decorrelated-jitter stream — deliberately NOT GLOBAL_RNG
# (that is the measurement stream; consuming it for sleep jitter would
# shift measurement outcomes and break the retry bit-identity contract)
_JITTER_RNG: List[Optional[object]] = [None]


def seed_backoff_jitter(seeds: Optional[Sequence[int]] = None) -> None:
    """(Re)seed the backoff-jitter stream.  Explicit ``seeds`` win, then
    ``QT_CHAOS_SEED``; otherwise time+pid — jitter exists to DESYNCHRONIZE
    concurrent retriers, so an unseeded default must differ per process."""
    from . import rng as _rng

    r = _rng._MeasurementRNG()
    if seeds is None:
        raw = os.environ.get(_CHAOS_SEED_ENV, "").strip()
        if raw:
            seeds = [int(raw)]
        else:
            seeds = [int(time.time() * 1e6), os.getpid()]  # qlint: allow(nondeterminism): unseeded jitter must decorrelate across processes; QT_CHAOS_SEED pins it
    r.seed([int(s) for s in seeds])
    _JITTER_RNG[0] = r


def backoff_delay(base: float, prev: Optional[float]) -> float:
    """One decorrelated-jitter backoff delay: uniform on
    [base, min(64*base, 3*prev)], seeded from :func:`seed_backoff_jitter`.
    Unlike the deterministic 1-2-4 ladder this never synchronizes a fleet
    of retriers that failed at the same instant, while keeping the same
    bounded envelope (never below ``base``, capped at ``64*base``)."""
    if _JITTER_RNG[0] is None:
        seed_backoff_jitter()
    base = max(float(base), 1e-9)
    cap = base * 64.0
    prev = base if (prev is None or prev <= 0.0) else float(prev)
    hi = max(base, min(cap, 3.0 * prev))
    return base + (hi - base) * float(_JITTER_RNG[0].uniform())


def retry_io(fn, *args, attempts: Optional[int] = None,
             base_delay: Optional[float] = None, what: str = "checkpoint IO",
             **kwargs):
    """Call ``fn`` retrying transient IO failures (OSError/TimeoutError)
    with bounded decorrelated-jitter backoff (:func:`backoff_delay`) —
    the wrapper around every orbax / metadata save+load.  A persistent
    failure re-raises the last error wrapped in a QuESTError naming the
    operation and attempt count."""
    if attempts is None:
        attempts = int(os.environ.get(_RETRY_ATTEMPTS_ENV, "4"))
    if base_delay is None:
        base_delay = float(os.environ.get(_RETRY_BASE_ENV, "0.05"))
    last = None
    delay: Optional[float] = None
    for k in range(max(1, attempts)):
        plan = _ACTIVE_FAULTS[0]
        if plan is not None and plan.take_io_fault():
            last = OSError(f"injected transient IO error ({what})")
        else:
            try:
                return fn(*args, **kwargs)
            except (OSError, TimeoutError) as e:  # includes IOError
                last = e
        _telemetry.inc("checkpoint_io_retries_total", what=what)
        if k + 1 < attempts:
            delay = backoff_delay(base_delay, delay)
            time.sleep(delay)
    raise QuESTError(
        f"{what}: failed after {attempts} attempts "
        f"(last error: {last!r})") from last


# ---------------------------------------------------------------------------
# Fault injection
# ---------------------------------------------------------------------------


class FaultPlan:
    """A deterministic schedule of injected faults, keyed on the ABSOLUTE
    window index of a resumable run (window w covers gates
    [w*every, (w+1)*every)).  Build programmatically
    (``FaultPlan("kill@2,io@3")``) or from the ``QT_FAULT_PLAN`` env var
    (:meth:`from_env`).  Kinds:

    - ``kill@W``      raise SimulatedPreemption before executing window W
    - ``killsave@W``  crash mid-save: after window W's checkpoint data is
                      scheduled but BEFORE the LATEST commit
    - ``corrupt@W``   after committing window W's generation, truncate its
                      amplitude payload and garbage its metadata
    - ``io@N``        the next N checkpoint IO operations raise a
                      transient OSError (absorbed by retry_io's backoff)
    - ``nan@W``       poke NaN into one shard of the amplitudes after
                      window W executes (before its watchdog check)
    - ``inf@W``       same with +Inf
    - ``scale@W``     multiply the amplitudes by 1.01 after window W
                      (norm drift for the ``renormalize`` policy)
    - ``stall@W``     window W's first exchange dispatch stalls past its
                      deadline once — absorbed by the collective guard's
                      retry budget (dist.guarded_dispatch), observable as
                      exchange_timeouts_total
    - ``shard_loss@W`` a shard dies during window W's exchange dispatch:
                      the guard raises dist.ShardLossError and
                      run_resumable fails over (rollback + mesh shrink)
    - ``host_loss@W`` a whole HOST's shards die during window W's
                      exchange dispatch (hierarchical topology,
                      parallel/topology.py): the ShardLossError carries
                      the observed shard, and the failover excludes the
                      dead host's entire device range so the surviving
                      mesh is built from intact hosts (2x4 -> 1x4)
    - ``oom@W``       window W's drain dispatch raises a synthetic
                      RESOURCE_EXHAUSTED once — caught by the memory
                      governor's OOM net (governor.oom_net), which
                      evicts idle registers, clears the plan caches,
                      and retries; arming ``oom@W`` TWICE exhausts the
                      single retry and proves the net re-raises

    Serve-level kinds, keyed on the :class:`quest_tpu.serve.SimServer`
    STEP index (consumed by the server's per-step hook
    :meth:`take_serve_fault`, not by run_resumable):

    - ``bank_fault@S`` the bank advanced at (or first after) step S hits
                      an injected transient fault: the server dissolves
                      it and its jobs retry in fresh banks
    - ``heal@S``      the operator heal signal fires at step S
                      (SimServer.heal(): drain to checkpoint boundaries,
                      re-expand onto the full mesh)
    - ``poison_job@J`` job id J is numerically poisoned: NaN is poked
                      into ITS batch element after every window it runs —
                      persistent (unlike the one-shot window events), so
                      the job re-poisons on every retry and the
                      quarantine bisection converges on it
    - ``shard_loss@S``/``host_loss@S`` under a server double as
                      step-keyed infrastructure loss (the server fails
                      over onto the shrunk mesh)

    Every fired event is appended to :attr:`log` so tests can assert the
    plan actually executed."""

    _KINDS = ("kill", "killsave", "corrupt", "io", "nan", "inf", "scale",
              "stall", "shard_loss", "host_loss", "oom",
              "bank_fault", "heal", "poison_job")

    def __init__(self, spec: str = ""):
        self.events: List[Tuple[str, int]] = []
        self.io_budget = 0
        self.poisoned_jobs: set = set()
        self.log: List[str] = []
        # exchange faults pending for the CURRENT window, armed by
        # run_resumable (arm_exchange_window) and consumed one per
        # dispatch attempt by dist.guarded_dispatch via
        # take_exchange_fault — window-keyed like every other kind, but
        # delivered at exchange-dispatch time, which has no window in
        # scope
        self._stalls_pending = 0
        self._loss_pending = False
        self._host_loss_pending = False
        self._oom_pending = 0
        spec = (spec or "").strip()
        if spec:
            for part in spec.split(","):
                kind, _, arg = part.strip().partition("@")
                kind = kind.strip()
                if kind not in self._KINDS:
                    raise QuESTError(
                        f"FaultPlan: unknown fault kind {kind!r} "
                        f"(expected one of {self._KINDS})")
                val = int(arg) if arg else 0
                if kind == "io":
                    self.io_budget += val
                elif kind == "poison_job":
                    self.poisoned_jobs.add(val)
                else:
                    self.events.append((kind, val))

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        spec = os.environ.get("QT_FAULT_PLAN", "")
        return cls(spec) if spec.strip() else None

    # -- hooks consumed by run_resumable / retry_io --

    def _fire(self, kind: str, window: int) -> bool:
        key = (kind, window)
        if key in self.events:
            self.events.remove(key)
            self.log.append(f"{kind}@{window}")
            return True
        return False

    def maybe_kill(self, window: int) -> None:
        if self._fire("kill", window):
            raise SimulatedPreemption(
                f"injected preemption before window {window}")

    def maybe_kill_mid_save(self, window: int) -> None:
        if self._fire("killsave", window):
            raise SimulatedPreemption(
                f"injected preemption mid-save of window {window}'s "
                "checkpoint (before commit)")

    def should_corrupt(self, window: int) -> bool:
        return self._fire("corrupt", window)

    def arm_exchange_window(self, window: int) -> None:
        """Move this window's ``stall``/``shard_loss``/``oom`` events
        into the pending slots the dispatch-time hooks consume."""
        if self._fire("stall", window):
            self._stalls_pending += 1
        if self._fire("shard_loss", window):
            self._loss_pending = True
        if self._fire("host_loss", window):
            self._host_loss_pending = True
        self.arm_oom(window)

    def arm_oom(self, window: int) -> None:
        """Move window W's ``oom`` events into the pending slot
        governor.oom_net consumes.  Called by arm_exchange_window under
        run_resumable; a bare fusion drain arms window 0 itself."""
        while self._fire("oom", window):
            self._oom_pending += 1

    def take_exchange_fault(self, op: str) -> Optional[str]:
        """The dist.EXCHANGE_FAULT_HOOK body: one pending fault per
        dispatch attempt, shard loss first (it preempts the window)."""
        if self._loss_pending:
            self._loss_pending = False
            return "shard_loss"
        if self._host_loss_pending:
            self._host_loss_pending = False
            return "host_loss"
        if self._stalls_pending > 0:
            self._stalls_pending -= 1
            return "stall"
        return None

    def take_oom_fault(self) -> bool:
        """governor.oom_net's injection hook: one synthetic
        RESOURCE_EXHAUSTED per pending ``oom`` event, consumed once per
        dispatch ATTEMPT — so a single armed event makes the net's one
        retry succeed, while two pending events burn the retry too and
        the failure propagates (the exhaustion path the tests pin)."""
        if self._oom_pending > 0:
            self._oom_pending -= 1
            return True
        return False

    def take_serve_fault(self, step: int) -> Optional[str]:
        """SimServer's per-step hook: fire at most one serve-level fault
        keyed on the server's global step index (banks interleave, so a
        bank-window key would be ambiguous).  Infrastructure loss first —
        it preempts everything else a step could do."""
        for kind in ("host_loss", "shard_loss", "bank_fault", "heal"):
            if self._fire(kind, step):
                return kind
        return None

    def poisoned(self, job_id: int) -> bool:
        """Whether ``poison_job@J`` marks this job id.  Deliberately NOT
        consumed on read: a poison job must re-poison on every retry or
        the bisection would exonerate it."""
        return int(job_id) in self.poisoned_jobs

    def take_io_fault(self) -> bool:
        if self.io_budget > 0:
            self.io_budget -= 1
            self.log.append("io")
            return True
        return False

    def maybe_corrupt_amps(self, qureg, window: int) -> None:
        """nan/inf/scale amplitude corruption, preserving any live
        permutation (the corruption is physical, like a real bit flip)."""
        for kind, val in (("nan", np.nan), ("inf", np.inf), ("scale", 1.01)):
            if not self._fire(kind, window):
                continue
            amps = qureg._amps_raw()
            perm = qureg._perm
            if kind == "scale":
                amps = amps * np.asarray(val, amps.dtype)
            else:
                # one poisoned amplitude in the LAST shard (highest index)
                amps = amps.at[0, amps.shape[1] - 1].set(val)
            qureg._set_amps_permuted(amps, perm)


# ---------------------------------------------------------------------------
# Numerical-health watchdog
# ---------------------------------------------------------------------------


_HEALTH_FNS: dict = {}


def _health_fn():
    """Jitted health scan: (worst norm, all-finite flag, worst element
    index) in ONE device program — on a sharded register the reductions
    are GSPMD psums — and one scalar readback for all three (the (3,)
    result array)."""
    import jax
    import jax.numpy as jnp

    fn = _HEALTH_FNS.get("fn")
    if fn is None:
        @jax.jit
        def fn(amps):
            if amps.ndim == 3:
                # a BatchedQureg bank: per-element norms; a non-finite
                # element dominates (badness=inf), then the norm FARTHEST
                # from 1 — argmax names the single worst ELEMENT so the
                # serving layer can attribute a poisoned bank to one job
                sq = amps[:, 0] * amps[:, 0] + amps[:, 1] * amps[:, 1]
                norms = jnp.sum(sq, axis=1)
                finite_e = jnp.all(jnp.isfinite(amps), axis=(1, 2))
                badness = jnp.where(finite_e, jnp.abs(norms - 1.0),
                                    jnp.inf)
                elem = jnp.argmax(badness)
                norm = norms[elem]
            else:
                sq = amps[0] * amps[0] + amps[1] * amps[1]
                norm = jnp.sum(sq)
                elem = jnp.zeros((), jnp.int32)
            finite = jnp.all(jnp.isfinite(amps))
            return jnp.stack([norm, finite.astype(amps.dtype),
                              elem.astype(amps.dtype)])

        _HEALTH_FNS["fn"] = fn
    return fn


def check_qureg_health(qureg) -> Tuple[float, bool]:
    """(sum |amps|^2, all-finite) of the register, via one jitted
    on-device reduction and one host readback.  Pending fused gates drain
    first, but a live permutation is NOT rematerialized — both reductions
    are permutation-invariant."""
    out = np.asarray(_health_fn()(qureg._amps_raw()))
    return float(out[0]), bool(out[1])


def check_bank_health(qureg) -> Tuple[float, bool, int]:
    """:func:`check_qureg_health` plus the worst batch-element index —
    same single device program and readback; the index is 0 for a scalar
    register."""
    out = np.asarray(_health_fn()(qureg._amps_raw()))
    return float(out[0]), bool(out[1]), int(out[2])


# watchdog policies; "raise" is the default (fail fast, keep the ckpt)
WATCHDOG_POLICIES = ("raise", "renormalize", "rollback")


def _health_tolerance(dtype) -> float:
    # norm drift beyond sqrt-eps of the working dtype means something is
    # genuinely wrong (a healthy fused pass preserves the norm to ~eps)
    return 1e-6 if np.dtype(dtype) == np.float64 else 1e-3


# ---------------------------------------------------------------------------
# Generation-based checkpoint protocol
# ---------------------------------------------------------------------------

_LATEST = "LATEST"
_COMMIT = "COMMITTED"
_GENS_KEPT = 2  # last-good + one predecessor (corruption fallback)


def _gen_name(cursor: int) -> str:
    return f"gen-{cursor:010d}"


def _gen_cursor(name: str) -> Optional[int]:
    if not name.startswith("gen-"):
        return None
    try:
        return int(name[4:])
    except ValueError:
        return None


def circuit_fingerprint(gates: Sequence, num_qubits: int, every: int) -> str:
    """Content hash binding a checkpoint to (circuit, register width,
    window cadence): resuming under ANY difference that would change the
    window plans is refused up front rather than silently diverging."""
    h = hashlib.sha256()
    h.update(f"n={num_qubits};every={every};gates={len(gates)};".encode())
    for g in gates:
        h.update(repr(tuple(g.targets)).encode())
        m = g.mat
        if isinstance(m, np.ndarray):
            h.update(m.tobytes())
    return h.hexdigest()


def save_generation(qureg, ckpt_dir: str, cursor: int, *,
                    fingerprint: str = "", faults: Optional[FaultPlan] = None,
                    window: int = -1) -> str:
    """Write generation ``cursor`` of ``qureg`` under ``ckpt_dir`` and
    commit it as last-good.  The amplitude payload is written
    asynchronously (orbax schedules the device->host copy synchronously,
    then persists in background); the commit — a COMMITTED marker plus an
    atomic LATEST pointer rename — happens only after the write finishes,
    so a crash at ANY point before commit leaves the previous LATEST
    generation intact and loadable.  Saves the RAW (possibly permuted)
    amplitudes plus ``Qureg._perm`` and the measurement-RNG state, the
    three extra pieces bit-exact resume needs beyond ``saveQureg``."""
    from . import checkpoint as CKPT
    from . import rng as _rng
    from .ops import measurement as M

    t0 = time.perf_counter()
    ckpt_dir = os.path.abspath(ckpt_dir)
    os.makedirs(ckpt_dir, exist_ok=True)
    gen = os.path.join(ckpt_dir, _gen_name(cursor))
    if os.path.exists(gen):  # stale uncommitted leftover from a crash
        shutil.rmtree(gen)
    os.makedirs(gen)
    amps = qureg._amps_raw()  # drain pending gates; keep the live perm
    ckptr = CKPT._checkpointer()
    retry_io(ckptr.save, os.path.join(gen, CKPT._AMPS_NAME),
             {"amps": amps}, force=True, what="saveQureg(amps)")
    meta = CKPT._qureg_meta(qureg)
    meta.update({
        "cursor": int(cursor),
        "perm": list(qureg._perm) if qureg._perm is not None else None,
        "fingerprint": fingerprint,
        "rng": _rng.GLOBAL_RNG.get_state(),
        "measure_keys": M.KEYS.get_state(),
        # a BatchedQureg's PER-ELEMENT measurement key bank (batch.py) —
        # None for scalar registers
        "batch_keys": qureg.key_state()
        if hasattr(qureg, "key_state") else None,
        # the writing mesh's shard count: informational for the elastic
        # restore path (load_latest reshards onto whatever mesh loads it;
        # strict_mesh=True refuses any difference)
        "mesh_shards": int(qureg.num_chunks),
    })
    retry_io(CKPT._write_meta, gen, meta, what="saveQureg(meta)")
    # ---- commit point ----
    retry_io(ckptr.wait_until_finished, what="saveQureg(wait)")
    if faults is not None:
        faults.maybe_kill_mid_save(window)
    with open(os.path.join(gen, _COMMIT), "w") as f:
        f.write(_gen_name(cursor) + "\n")
    tmp = os.path.join(ckpt_dir, _LATEST + ".tmp")
    with open(tmp, "w") as f:
        f.write(_gen_name(cursor) + "\n")
    os.replace(tmp, os.path.join(ckpt_dir, _LATEST))
    if faults is not None and faults.should_corrupt(window):
        _corrupt_generation(gen)
    _prune_generations(ckpt_dir, keep=_GENS_KEPT)
    _telemetry.inc("checkpoints_total")
    _telemetry.observe("checkpoint_commit_seconds",
                       time.perf_counter() - t0)
    return gen


def _corrupt_generation(gen: str) -> None:
    """Injected corruption: truncate every data file and garbage the
    metadata — models a torn write / bad disk."""
    for root, _dirs, files in os.walk(gen):
        for fname in files:
            if fname == _COMMIT:
                continue
            p = os.path.join(root, fname)
            with open(p, "wb") as f:
                f.write(b"\x00CORRUPT\x00")


def _committed_generations(ckpt_dir: str) -> List[int]:
    """Committed generation cursors, newest first."""
    out = []
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return []
    for name in names:
        c = _gen_cursor(name)
        if c is None:
            continue
        if os.path.exists(os.path.join(ckpt_dir, name, _COMMIT)):
            out.append(c)
    return sorted(out, reverse=True)


def latest_committed_cursor(ckpt_dir: str) -> Optional[int]:
    """Cursor of the newest COMMITTED generation under ``ckpt_dir``, or
    None — the rollback target serve's failover uses to decide whether a
    live bank can resume from checkpoint or must dissolve and retry."""
    gens = _committed_generations(os.path.abspath(ckpt_dir))
    return gens[0] if gens else None


def _prune_generations(ckpt_dir: str, keep: int) -> None:
    """Drop all but the ``keep`` newest committed generations.  An
    UNCOMMITTED generation newer than every committed one is an in-flight
    write (possibly another process's) and is left alone."""
    committed = _committed_generations(ckpt_dir)
    keep_set = {_gen_name(c) for c in committed[:keep]}
    newest = committed[0] if committed else -1
    for name in os.listdir(ckpt_dir):
        c = _gen_cursor(name)
        if c is None or name in keep_set:
            continue
        is_committed = os.path.exists(os.path.join(ckpt_dir, name, _COMMIT))
        if not is_committed and c > newest:
            continue
        shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)


def _validated_perm(perm, n: int):
    """Re-derive the carried logical->physical permutation for a restore:
    the perm is a bit-level permutation of the GLOBAL amplitude index, so
    it is valid on ANY mesh shape unchanged — what changes across meshes
    is only which of its positions are shard-coordinate bits, and every
    consumer (remap_sharded, the window planner) derives that from the
    live mesh.  Malformed values (wrong length, not a permutation — a
    torn metadata write) raise ValueError so load_latest treats the
    generation as corrupt and falls back."""
    if perm is None:
        return None
    perm = tuple(int(p) for p in perm)
    if sorted(perm) != list(range(n)):
        raise ValueError(
            f"checkpoint perm {perm!r} is not a permutation of "
            f"range({n})")
    return perm


def _load_generation(ckpt_dir: str, cursor: int, env, *,
                     strict_mesh: bool = False):
    from . import checkpoint as CKPT

    gen = os.path.join(ckpt_dir, _gen_name(cursor))
    meta = CKPT._read_meta(gen)
    saved_shards = meta.get("mesh_shards")
    if saved_shards is not None and int(saved_shards) != env.num_devices:
        if strict_mesh:
            raise QuESTError(
                "load_latest: checkpoint mesh mismatch — generation "
                f"{_gen_name(cursor)} was written on {saved_shards} "
                f"shards but this environment has {env.num_devices} "
                "devices, and strict_mesh=True refuses elastic restore")
        # elastic restore: _restore_amps below hands orbax the TARGET
        # sharding, so the global (2, 2^n) payload reshards on read —
        # the physical amplitude layout is mesh-shape-independent
        # (leading index bits), only its partition moves
        _telemetry.inc("elastic_restores_total")
        _log_event(meta.get("fingerprint", "")[:12] or "-", "elastic_restore",
                   cursor=int(meta.get("cursor", 0)),
                   from_shards=int(saved_shards),
                   to_shards=int(env.num_devices))
    q = CKPT._qureg_from_meta(meta, env)
    amps = CKPT._restore_amps(gen, q)
    perm = _validated_perm(meta.get("perm"), q.num_qubits_in_state_vec)
    q._set_amps_permuted(amps, perm)
    if meta.get("batch_keys") is not None and hasattr(q, "set_key_state"):
        q.set_key_state(meta["batch_keys"])
    return q, meta


def load_latest(ckpt_dir: str, env, *, strict_mesh: bool = False):
    """Load the newest loadable committed generation under ``ckpt_dir``.
    Returns (qureg, meta) or None when no checkpoint exists.  A corrupt
    newest generation (torn write, bad disk) falls back to its
    predecessor with a warning; genuine environment mismatches
    (precision/qubit count vs this env) are surfaced as QuESTError, not
    swallowed.

    Restore is ELASTIC by default: a generation written on an M-shard
    mesh loads onto ``env``'s N-shard mesh for any power-of-two N the
    register can shard over (including N=1) — the raw amplitude payload
    reshards on read and the carried perm/cursor/RNG state are
    re-derived/validated (docs/design.md §19).  ``strict_mesh=True``
    restores the old behavior: any shard-count difference is a
    structured QuESTError."""
    ckpt_dir = os.path.abspath(ckpt_dir)
    if not os.path.isdir(ckpt_dir):
        return None
    candidates = _committed_generations(ckpt_dir)
    # prefer the LATEST pointer's target ordering but never trust it
    # blindly — it may name a pruned or corrupted generation
    try:
        with open(os.path.join(ckpt_dir, _LATEST)) as f:
            pointed = _gen_cursor(f.read().strip())
        if pointed in candidates:
            candidates.remove(pointed)
            candidates.insert(0, pointed)
    except OSError:
        pass
    if not candidates:
        return None
    last_err = None
    for cursor in candidates:
        try:
            loaded = _load_generation(ckpt_dir, cursor, env,
                                      strict_mesh=strict_mesh)
            _telemetry.inc("checkpoint_restores_total")
            return loaded
        except QuESTError:
            raise  # structured mismatch (precision/qubits): not corruption
        # qlint: allow(broad-except): corruption shows up as whatever the codec raises (json/struct/OSError/...); any unreadable generation falls back to an older one, with the error surfaced in the warning
        except Exception as e:  # corrupt payload/metadata: try older gen
            last_err = e
            warnings.warn(
                f"run_resumable: checkpoint generation {cursor} at "
                f"{ckpt_dir} is unreadable ({e!r}); falling back to an "
                "older generation", stacklevel=2)
    raise QuESTError(
        f"run_resumable: no loadable checkpoint generation under "
        f"{ckpt_dir} (last error: {last_err!r})")


# ---------------------------------------------------------------------------
# Window-stepping executor (shared by run_resumable and quest_tpu.serve)
# ---------------------------------------------------------------------------


class WindowExecutor:
    """Drive a gate stream on a register ONE fusion window at a time.

    The window boundaries come from
    :func:`quest_tpu.circuit.plan_checkpoint_boundaries` — the safe
    yield points where no fused pass is mid-flight, so between any two
    :meth:`step` calls the register can be checkpointed, preempted, or
    interleaved with other work.  Two consumers share this loop:

    - :func:`run_resumable` steps an executor to completion, wrapping
      every window with the watchdog and a committed checkpoint
      generation (``_execute_windows``);
    - :class:`quest_tpu.serve.SimServer` interleaves the windows of MANY
      executors under a fair scheduler (continuous batching), calling
      :meth:`checkpoint` only when a bank is preempted.

    ``step()`` fires the window's armed faults (kill before execute,
    exchange faults at dispatch time) exactly as run_resumable's loop
    always has, so FaultPlan schedules apply unchanged to served banks.
    """

    def __init__(self, qureg, gates: Sequence, *, every: int,
                 start: int = 0, faults: Optional[FaultPlan] = None,
                 fingerprint: str = ""):
        from . import circuit as C

        if every < 1:
            raise QuESTError("WindowExecutor: every must be >= 1")
        self.qureg = qureg
        self.gates = [g if isinstance(g, C.Gate)
                      else C.Gate(tuple(g[0]), g[1]) for g in gates]
        self.every = int(every)
        self.faults = faults
        self.fingerprint = fingerprint
        self.cursor = int(start)
        self._boundaries = C.plan_checkpoint_boundaries(
            len(self.gates), self.every, start=self.cursor)
        self._bi = 0
        # gate range [begin, end) of the most recent step(), for
        # check_health's fault attribution
        self.last_window: Optional[Tuple[int, int]] = None

    @property
    def done(self) -> bool:
        return self._bi >= len(self._boundaries)

    @property
    def window(self) -> int:
        """Index of the NEXT window to execute (gates
        [window*every, (window+1)*every))."""
        return self.cursor // self.every

    @property
    def num_windows(self) -> int:
        return len(self._boundaries)

    def step(self) -> int:
        """Execute one window [cursor, next boundary) as a single fused
        drain and advance the cursor.  Returns the new cursor.  No-op at
        the end of the stream."""
        from . import fusion as _fusion

        if self.done:
            return self.cursor
        end = self._boundaries[self._bi]
        if self.faults is not None:
            self.faults.maybe_kill(self.window)
            self.faults.arm_exchange_window(self.window)
        # the checkpoint cursor indexes the RAW gate list and a resume
        # may land on a different mesh/perm than this step runs under, so
        # the cost-gated circuit rewrite must not fire per window — see
        # optimizer.suppressed
        from . import optimizer as _opt

        with _opt.suppressed():
            _fusion.start_gate_fusion(self.qureg)
            try:
                self.qureg._fusion.gates.extend(
                    self.gates[self.cursor:end])
            finally:
                _fusion.stop_gate_fusion(self.qureg)  # the window pass
        self.last_window = (self.cursor, end)
        self.cursor = end
        self._bi += 1
        return end

    def check_health(self) -> None:
        """Numerical-health check at the current window boundary — the
        fault-surfacing half the serving layer drives (run_resumable has
        its own policy-bearing watchdog in ``_execute_windows``).  Raises
        :class:`NumericalHealthError` naming the just-executed gate range
        and, for a batched bank, the worst element index — the quarantine
        bisection's direct-attribution fast path."""
        q = self.qureg
        norm, finite, elem = check_bank_health(q)
        # density matrices: purity < 1 is legitimate physics, so only
        # finiteness is checked (mirrors run_resumable's watchdog)
        norm_bad = (not q.is_density_matrix
                    and abs(norm - 1.0) > _health_tolerance(q.dtype))
        if finite and not norm_bad:
            return
        is_bank = getattr(q, "batch_size", 0) > 1
        desc = ("non-finite amplitudes" if not finite
                else f"norm {norm!r} drifted beyond tolerance")
        raise NumericalHealthError(
            f"health check failed after gates {self.last_window}: {desc}"
            + (f" (worst element {elem})" if is_bank else ""),
            window=self.last_window, norm=norm, finite=finite,
            element=elem if is_bank else None)

    def checkpoint(self, ckpt_dir: str) -> str:
        """Commit a generation of the register at the CURRENT cursor (a
        window boundary) — the preempt-to-checkpoint half of serve's
        preemption protocol; resume via :func:`load_latest` +
        :func:`_restore_into` and a fresh executor with
        ``start=cursor``."""
        window = max(0, (self.cursor - 1) // self.every)
        return save_generation(self.qureg, ckpt_dir, self.cursor,
                               fingerprint=self.fingerprint,
                               faults=self.faults, window=window)


# ---------------------------------------------------------------------------
# Resumable driver
# ---------------------------------------------------------------------------


def run_resumable(qureg, gates: Sequence, ckpt_dir: str, *, every: int = 64,
                  watchdog: str = "raise",
                  faults: Optional[FaultPlan] = None,
                  elastic: bool = True):
    """Execute ``gates`` (a sequence of :class:`quest_tpu.circuit.Gate`,
    or ``(targets, mat)`` pairs, on state-vector bit positions) on
    ``qureg`` in fusion windows of ``every`` gates, checkpointing at every
    window boundary — never mid-window — into ``ckpt_dir``.

    If ``ckpt_dir`` already holds a committed checkpoint for this
    (circuit, register, cadence) — matched by content fingerprint — the
    run RESUMES from its cursor: the register is rebound to the saved
    amplitudes (raw, with the live logical->physical permutation
    restored), the measurement RNG state is restored, and the remaining
    windows execute exactly as the uninterrupted run would, producing
    bit-identical amplitudes.

    ``watchdog``: one of ``raise`` / ``renormalize`` / ``rollback``
    (see module docstring).  ``faults``: a :class:`FaultPlan`; defaults
    to ``QT_FAULT_PLAN`` when set.  Returns ``qureg``.

    ``elastic`` (default True) enables degraded-mesh failover: when a
    guarded exchange dispatch declares a shard dead
    (dist.ShardLossError), the run rolls back to the last-good
    generation, shrinks the mesh to the surviving half (halving until a
    single device remains), reshards the rolled-back state onto it via
    the elastic restore path, records the event (failovers_total,
    degradation registry, a ``failover`` JSON log line with the
    detect/rollback/reshard phase breakdown), and resumes.  Requires at
    least one committed generation to roll back to; with ``elastic=False``
    or on a single-device mesh the ShardLossError propagates."""
    from . import circuit as C

    if watchdog not in WATCHDOG_POLICIES:
        raise QuESTError(
            f"run_resumable: unknown watchdog policy {watchdog!r} "
            f"(expected one of {WATCHDOG_POLICIES})")
    if every < 1:
        raise QuESTError("run_resumable: every must be >= 1")
    glist = [g if isinstance(g, C.Gate) else C.Gate(tuple(g[0]), g[1])
             for g in gates]
    if faults is None:
        faults = FaultPlan.from_env()
    fp = circuit_fingerprint(glist, qureg.num_qubits_in_state_vec, every)
    run_id = fp[:12]
    t_run = time.perf_counter()

    start = 0
    loaded = load_latest(ckpt_dir, qureg.env)
    if loaded is not None:
        restored, meta = loaded
        if meta.get("fingerprint") not in ("", fp):
            raise QuESTError(
                "run_resumable: checkpoint at "
                f"{ckpt_dir} was written by a different circuit/cadence "
                f"(saved fingerprint {meta.get('fingerprint')!r} != this "
                f"run's {fp!r}); refusing to resume")
        _restore_into(qureg, restored, meta)
        start = int(meta.get("cursor", 0))
        _log_event(run_id, "restore", cursor=start,
                   generation=_gen_name(start), window=start // every,
                   elapsed=round(time.perf_counter() - t_run, 4))

    from .parallel import dist as PAR

    _ACTIVE_FAULTS[0] = faults
    PAR.EXCHANGE_FAULT_HOOK[0] = (faults.take_exchange_fault
                                  if faults is not None else None)
    # mutable per-attempt markers for the failover MTTR phases: the
    # executor stamps when the current window began (detect = time from
    # there to the ShardLossError catch) and, after a failover, when the
    # first post-resume window completes (the resume phase)
    marks = {"window_started": None, "resume_from": None}
    try:
        while True:
            try:
                _execute_windows(qureg, glist, ckpt_dir, every=every,
                                 watchdog=watchdog, faults=faults, fp=fp,
                                 run_id=run_id, t_run=t_run, start=start,
                                 marks=marks)
                return qureg
            except PAR.ShardLossError as err:
                start = _failover(qureg, ckpt_dir, err, run_id=run_id,
                                  t_run=t_run, elastic=elastic,
                                  window_started=marks["window_started"])
                marks["resume_from"] = time.perf_counter()
    finally:
        _ACTIVE_FAULTS[0] = None
        PAR.EXCHANGE_FAULT_HOOK[0] = None


def _execute_windows(qureg, glist, ckpt_dir: str, *, every: int,
                     watchdog: str, faults: Optional[FaultPlan], fp: str,
                     run_id: str, t_run: float, start: int,
                     marks: dict) -> None:
    """One pass of run_resumable's window loop from gate ``start`` to the
    end of ``glist`` on qureg's CURRENT mesh — factored out so the
    failover path can re-enter it after a rollback + mesh shrink.  The
    window stepping itself is :class:`WindowExecutor` (shared with the
    serving layer); this wrapper adds the watchdog, fault-driven
    amplitude corruption, and a committed checkpoint after EVERY window.
    """
    ex = WindowExecutor(qureg, glist, every=every, start=start,
                        faults=faults, fingerprint=fp)
    while not ex.done:
        window = ex.window
        begin = ex.cursor
        marks["window_started"] = time.perf_counter()
        end = ex.step()
        if marks["resume_from"] is not None:
            _telemetry.set_gauge("failover_resume_seconds",
                                 time.perf_counter() - marks["resume_from"])
            marks["resume_from"] = None
        if faults is not None:
            faults.maybe_corrupt_amps(qureg, window)
        _watchdog_step(qureg, ckpt_dir, watchdog, (begin, end),
                       log_ctx=(run_id, t_run))
        t_ck = time.perf_counter()
        with _telemetry.span("resilience.checkpoint", window=window):
            ex.checkpoint(ckpt_dir)
        _log_event(run_id, "checkpoint", window=window, cursor=end,
                   generation=_gen_name(end),
                   seconds=round(time.perf_counter() - t_ck, 4),
                   elapsed=round(time.perf_counter() - t_run, 4))


def _failover(qureg, ckpt_dir: str, err, *, run_id: str, t_run: float,
              elastic: bool, window_started: Optional[float]) -> int:
    """Degraded-mesh failover: roll the register back to the last-good
    generation RESHARDED onto a mesh of the surviving half of the
    devices, and return the gate cursor to resume from.  Re-raises the
    ShardLossError when failover is disabled, the mesh is already a
    single device, or no committed generation exists to roll back to."""
    from . import env as _env

    t_detect = time.perf_counter()
    old_n = qureg.env.num_devices
    if not elastic or old_n <= 1:
        raise err
    new_n = old_n // 2
    detect_s = (t_detect - window_started) if window_started else 0.0
    # host-aware exclusion (parallel/topology.py): when the loss names a
    # shard and the mesh is hierarchical, the whole host holding that
    # shard is presumed dead — its entire device range is excluded so
    # the surviving mesh is built from intact hosts only (a 2x4
    # arrangement fails over onto the other host's 1x4, not onto a mix
    # of live and dead chips)
    dead_host = None
    excl = None
    topology = getattr(qureg.env, "topology", None)
    if (err.shard is not None and topology is not None
            and topology.hosts > 1):
        dead_host = topology.host_of(int(err.shard))
        excl = list(topology.host_range(dead_host))
        if old_n - len(excl) < new_n:
            excl = excl[:old_n - new_n]
    # rollback: pick + read the last-good generation, restoring its raw
    # payload directly into the SHRUNKEN mesh's sharding (the elastic
    # path — one restore does both the rollback and the reshard IO)
    t0 = time.perf_counter()
    new_env = _env.shrink_env(qureg.env, new_n, exclude_indices=excl)
    loaded = load_latest(ckpt_dir, new_env)
    rollback_s = time.perf_counter() - t0
    if loaded is None:
        raise QuESTError(
            f"run_resumable: shard loss during {err.op!r} dispatch but no "
            f"committed generation exists under {ckpt_dir} to roll back "
            "to; cannot fail over") from err
    # reshard: rebind the register to the degraded mesh + restored state
    t1 = time.perf_counter()
    restored, meta = loaded
    qureg.env = new_env
    _restore_into(qureg, restored, meta)
    cursor = int(meta.get("cursor", 0))
    reshard_s = time.perf_counter() - t1
    _telemetry.inc("failovers_total")
    _telemetry.set_gauge("failover_detect_seconds", detect_s)
    _telemetry.set_gauge("failover_rollback_seconds", rollback_s)
    _telemetry.set_gauge("failover_reshard_seconds", reshard_s)
    host_note = (f" (host {dead_host} excluded)"
                 if dead_host is not None else "")
    record_degradation(
        f"mesh_failover_{old_n}to{new_n}",
        f"shard loss during {err.op!r} dispatch ({err}); mesh shrunk "
        f"{old_n}->{new_n}{host_note}, resumed from gate cursor {cursor}")
    _log_event(run_id, "failover", op=err.op, from_shards=old_n,
               to_shards=new_n, cursor=cursor, dead_host=dead_host,
               detect_seconds=round(detect_s, 4),
               rollback_seconds=round(rollback_s, 4),
               reshard_seconds=round(reshard_s, 4),
               elapsed=round(time.perf_counter() - t_run, 4))
    return cursor


def _restore_into(qureg, restored, meta) -> None:
    """Rebind ``qureg`` to a loaded generation's state (amps + perm +
    dtype) and restore the measurement RNG streams."""
    from . import rng as _rng
    from .ops import measurement as M

    if restored.num_qubits_in_state_vec != qureg.num_qubits_in_state_vec \
            or restored.is_density_matrix != qureg.is_density_matrix:
        raise QuESTError(
            "run_resumable: checkpoint register shape "
            f"({restored.num_qubits_represented} qubits, density="
            f"{restored.is_density_matrix}) does not match the target "
            f"register ({qureg.num_qubits_represented} qubits, density="
            f"{qureg.is_density_matrix})")
    rb = int(getattr(restored, "batch_size", 0) or 0)
    qb = int(getattr(qureg, "batch_size", 0) or 0)
    if rb != qb:
        raise QuESTError(
            "run_resumable: checkpoint batch mismatch — the generation "
            + (f"holds a bank of {rb} elements" if rb
               else "holds a scalar register")
            + " but the target register "
            + (f"is a bank of {qb} elements" if qb else "is scalar")
            + "; a batched checkpoint only restores into a BatchedQureg "
            "of the same batch size")
    qureg.bind_checkpoint_state(restored._amps, restored._perm,
                                restored.dtype)
    if meta.get("batch_keys") is not None \
            and hasattr(qureg, "set_key_state"):
        qureg.set_key_state(meta["batch_keys"])
    if meta.get("rng") is not None:
        _rng.GLOBAL_RNG.set_state(meta["rng"])
    if meta.get("measure_keys") is not None:
        M.KEYS.set_state(meta["measure_keys"])


def _watchdog_step(qureg, ckpt_dir: str, policy: str,
                   window: Tuple[int, int],
                   log_ctx: Optional[Tuple[str, float]] = None) -> None:
    def _verdict(v: str) -> None:
        _telemetry.inc("watchdog_verdicts_total", policy=policy, verdict=v)
        if v != "ok":
            # the flight ring records the interesting verdicts; routine
            # "ok" checks would wash real incidents out of a bounded ring
            _telemetry.flight_event("watchdog", policy=policy, verdict=v,
                                    window=f"{window[0]}..{window[1]}")
        if log_ctx is not None:
            run_id, t_run = log_ctx
            _log_event(run_id, "watchdog", window=list(window), verdict=v,
                       norm=round(norm, 9), finite=finite,
                       elapsed=round(time.perf_counter() - t_run, 4))

    norm, finite = check_qureg_health(qureg)
    tol = _health_tolerance(qureg.dtype)
    drift = abs(norm - 1.0)
    # density matrices: sum |rho_ij|^2 is the purity, <= 1 and legitimately
    # < 1 under noise — only finiteness is checked for them
    norm_bad = (not qureg.is_density_matrix) and drift > tol
    if finite and not norm_bad:
        _verdict("ok")
        return
    desc = ("non-finite amplitudes" if not finite
            else f"norm drift |{norm:.6g} - 1| > {tol:g}")
    msg = (f"numerical-health check failed in window "
           f"[{window[0]}, {window[1]}): {desc}")
    if finite and policy == "renormalize":
        # norm drift only: rescale in place (keeps the live permutation)
        import jax.numpy as jnp

        amps = qureg._amps_raw()
        perm = qureg._perm
        scale = jnp.asarray(1.0 / np.sqrt(norm), amps.dtype)
        qureg._set_amps_permuted(amps * scale, perm)
        warnings.warn(f"run_resumable: {msg}; renormalized", stacklevel=2)
        _verdict("renormalized")
        return
    if policy == "rollback":
        loaded = load_latest(ckpt_dir, qureg.env)
        if loaded is not None:
            restored, meta = loaded
            _restore_into(qureg, restored, meta)
            _verdict("rollback")
            raise NumericalHealthError(
                f"{msg}; rolled back to last-good checkpoint at gate "
                f"cursor {meta.get('cursor', 0)} — re-run run_resumable "
                "to resume from it",
                window=window, norm=norm, finite=finite,
                rolled_back_to=int(meta.get("cursor", 0)))
        _verdict("rollback_failed")
        raise NumericalHealthError(
            f"{msg}; no last-good checkpoint exists to roll back to",
            window=window, norm=norm, finite=finite)
    _verdict("raise")
    raise NumericalHealthError(msg, window=window, norm=norm, finite=finite)
